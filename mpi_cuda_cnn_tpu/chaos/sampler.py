"""Seeded fault-schedule sampler over the live `faults.SITES` registry.

Every fault plan the repo proved PRs 4-17 against was hand-written: a
handful of author-chosen schedules per feature. Lineage-driven fault
injection (Alvaro et al., SIGMOD'15) and FATE & DESTINI (Gunawi et
al., NSDI'11) showed that the bugs worth finding live in the
cross-products no hand plan covers — a pool collapse during an
autoscaler drain, a zombie handoff racing a kv_corrupt readmit. This
module is the search half of that idea: draw random multi-fault plans
from the SAME registry `--fault-plan` validates against (kinds x sites
x trigger ticks x params), weighted toward cross-kind interleavings,
and serialize each draw back through `faults.format_plan` so every
sampled episode is a one-line repro.

The sampler is registry-driven on purpose: a kind or site added to
`faults.SITES["fleet-bench"]` becomes searchable the moment it exists,
with no chaos-side edit — the axis gates below only SUBTRACT (sites a
given episode's topology never reaches), never enumerate.
"""

from __future__ import annotations

import dataclasses
import random

from ..faults import SITES, Fault, format_plan, validate_plan_sites

# The CLI surface whose registered sites the sampler draws from — the
# fleet storm is the one surface where every fault domain (membership,
# handoff, resume, spill) composes.
SURFACE = "fleet-bench"

# fire("fleet.tick") raises these straight out of Fleet.run — simulated
# whole-PROCESS death. There is no post-episode state left to check
# invariants on, so the schedule search skips them; every other
# registered kind is fair game.
RAISING_KINDS = frozenset({"crash", "io"})


@dataclasses.dataclass(frozen=True)
class EpisodeAxes:
    """The topology/feature axes one episode samples over — the
    prefix + spec + disagg + spill + autoscale matrix (ISSUE 19). The
    axes gate which fault sites are LIVE (a handoff fault on a unified
    fleet would fail Fleet's inert-fault validation; a spill fault
    without a host tier would silently never fire)."""

    # --pools grammar ("prefill:P,decode:D") disagg split, None=unified
    pools: str | None = None
    prefix: bool = False       # shared prefix cache
    spill: bool = False        # host-tier spill (requires prefix)
    spec: str = "off"          # speculative decoding: off | lookup
    autoscale: bool = False    # online goodput autoscaler
    transport: bool = False    # lossy message bus + leases (ISSUE 20)

    def label(self) -> str:
        parts = [f"pools={self.pools}" if self.pools else "unified"]
        if self.prefix:
            parts.append("prefix")
        if self.spill:
            parts.append("spill")
        if self.spec != "off":
            parts.append(f"spec={self.spec}")
        if self.autoscale:
            parts.append("autoscale")
        if self.transport:
            parts.append("transport")
        return ",".join(parts)


def sample_axes(rng: random.Random) -> EpisodeAxes:
    """One seeded draw over the axes matrix. Probabilities lean toward
    feature-ON (the whole point is the interactions); spill stays
    conditioned on prefix — the host tier spills prefix-tree pages, so
    the combination is a constructor error, not a samplable point."""
    pools = rng.choice([None, None, "prefill:1,decode:2",
                        "prefill:2,decode:1"])
    prefix = rng.random() < 0.5
    return EpisodeAxes(
        pools=pools,
        prefix=prefix,
        spill=prefix and rng.random() < 0.5,
        spec="lookup" if rng.random() < 0.4 else "off",
        autoscale=rng.random() < 0.35,
        # The bus routes the unified control plane only — transport +
        # pools is a Fleet constructor error (the handoff plane stays
        # direct-call), so like spill-without-prefix it is not a
        # samplable point.
        transport=pools is None and rng.random() < 0.5,
    )


def _live_pairs(axes: EpisodeAxes) -> list[tuple[str, str]]:
    """The (site, kind) pairs this episode's topology can actually
    reach, from the live registry: fleet.handoff exists only on a
    pooled fleet (Fleet rejects the plan as inert otherwise),
    tier.spill only with the host tier on, pool_crash only with pools
    to crash. Sorted for seed-stable iteration order."""
    pairs = []
    for site, kinds in sorted(SITES[SURFACE].items()):
        if site == "fleet.handoff" and not axes.pools:
            continue
        if site == "tier.spill" and not axes.spill:
            continue
        if site == "fleet.transport" and not axes.transport:
            continue
        for kind in sorted(kinds - RAISING_KINDS):
            if kind == "pool_crash" and not axes.pools:
                continue
            pairs.append((site, kind))
    return pairs


def _sample_args(rng: random.Random, site: str, kind: str,
                 axes: EpisodeAxes, *, replicas: int) -> dict:
    """Seeded params for one fault, kept inside what the fleet accepts
    (replica indices that have joined by construction, pool names that
    exist). Optional knobs (zombie_ticks) appear with some probability
    — they are exactly what the shrinker's coordinate pass later tries
    to drop."""
    args: dict = {}
    if kind in ("replica_crash", "replica_leave"):
        args["replica"] = rng.randrange(replicas)
        if kind == "replica_crash" and rng.random() < 0.35:
            args["zombie_ticks"] = rng.randint(1, 4)
    elif kind == "pool_crash":
        args["pool"] = rng.choice(["prefill", "decode"])
        if rng.random() < 0.25:
            args["zombie_ticks"] = rng.randint(1, 3)
    elif kind == "replica_join":
        if rng.random() < 0.5:
            args["replicas"] = rng.randint(1, 2)
        if axes.pools and rng.random() < 0.5:
            args["pool"] = rng.choice(["prefill", "decode"])
    elif kind == "kv_corrupt" and site == "fleet.handoff":
        args["page"] = rng.randrange(4)
    elif kind == "partition":
        args["replica"] = rng.randrange(replicas)
        args["ticks"] = rng.randint(4, 12)
    elif kind == "msg_delay":
        args["ticks"] = rng.randint(1, 6)
        if rng.random() < 0.5:
            args["count"] = rng.randint(1, 3)
        if rng.random() < 0.5:
            args["kind"] = rng.choice(["commit", "dispatch",
                                       "terminal", "hb"])
    elif kind in ("msg_drop", "msg_dup"):
        args["count"] = rng.randint(1, 3)
        if rng.random() < 0.5:
            args["kind"] = rng.choice(["commit", "dispatch",
                                       "terminal", "hb"])
    return args


def _sample_at(rng: random.Random, site: str, *, max_tick: int) -> int:
    """Trigger values per site class: fleet.tick triggers on the fleet
    tick counter; the polled sites trigger on their own SEQUENCE
    numbers (Nth handoff / resume re-dispatch / spill), which stay
    small at episode scale."""
    if site in ("fleet.tick", "fleet.transport"):
        # Both trigger on the fleet tick counter (transport faults arm
        # at the top of the named tick via apply_tick_faults).
        return rng.randint(1, max_tick)
    return rng.randrange(7)


def sample_plan(rng: random.Random, axes: EpisodeAxes, *,
                replicas: int, max_tick: int = 96) -> str:
    """Draw one multi-fault plan, serialized to the `--fault-plan`
    grammar.

    Weighted toward CROSS-KIND interleavings: the entry count leans
    multi-fault (2-4 common), and kinds are drawn without replacement
    first — distinct kinds before repeats — because the untested
    surface is kind A's recovery racing kind B's trigger, not the Nth
    instance of A. Entries are sorted by trigger tick within
    fleet.tick draws only where it costs nothing: plan order is
    semantically irrelevant (the injector matches on (site, at)), so
    the spelling stays exactly as drawn for seed stability."""
    pairs = _live_pairs(axes)
    n = rng.choices([1, 2, 3, 4, 5], weights=[1, 4, 5, 4, 1])[0]
    picks: list[tuple[str, str]] = []
    unseen = list(pairs)
    seen_kinds: set[str] = set()
    for _ in range(n):
        fresh = [p for p in unseen if p[1] not in seen_kinds]
        pool = fresh if fresh else pairs
        site, kind = rng.choice(pool)
        picks.append((site, kind))
        seen_kinds.add(kind)
    plan = [
        Fault(kind=kind, site=site,
              at=_sample_at(rng, site, max_tick=max_tick),
              args=_sample_args(rng, site, kind, axes, replicas=replicas))
        for site, kind in picks
    ]
    # Self-check against the registry the CLI validates with: a sampled
    # plan that --fault-plan would reject is a sampler bug, and it must
    # surface at sample time, not mid-episode.
    validate_plan_sites(plan, SURFACE)
    return format_plan(plan)
