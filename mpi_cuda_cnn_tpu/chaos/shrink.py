"""Automatic plan minimization: ddmin over entries, then coordinates.

A sampled violation usually arrives wrapped in noise — four faults in
the plan, three irrelevant. Classic delta debugging (ddmin) strips the
plan to a locally-minimal entry set: every remaining fault is
necessary (removing any one makes the episode pass). A second pass
then minimizes INSIDE each surviving entry — trigger ticks walk down
toward the site's floor, optional params drop, numeric params shrink —
so the emitted repro is not just few faults but the *earliest,
plainest* spelling of each. Both passes re-run the fully deterministic
episode at every probe (the bitwise re-run guarantee is what makes a
probe's verdict trustworthy), and verdicts are cached by plan
spelling so the search never pays for the same probe twice.

The minimization target is "still fails the oracle", not "fails the
same way" — with one caveat: probes are only accepted while the
violation CLASS set stays within the original's (a probe that trades a
replay drift for a config-error exception would minimize into a
different bug)."""

from __future__ import annotations

import dataclasses

from ..faults import Fault, format_plan, parse_plan
from .episode import EpisodeConfig, run_episode

# Optional per-kind args the coordinate pass may DROP outright.
# Required ones (replica targets, pool names) stay: dropping them
# re-targets the fault (replica defaults to r0, pool_crash without a
# pool is a config error) — a different schedule, not a smaller one.
# "kind" is the transport message-kind filter (ISSUE 20): dropping it
# widens the fault to ANY message, a strictly plainer spelling.
_DROPPABLE = ("zombie_ticks", "kind")
# Numeric args the coordinate pass walks toward their floor. count=1
# is one faulted message; ticks=1 is the shortest delay / partition
# window the transport grammar accepts.
_SHRINK_FLOORS = {"replicas": 1, "page": 0, "count": 1, "ticks": 1}


class _Prober:
    """Run-and-cache: one oracle verdict per distinct plan spelling."""

    def __init__(self, cfg: EpisodeConfig, allowed_checks: set[str]):
        self.cfg = cfg
        self.allowed = allowed_checks
        self.cache: dict[str, bool] = {}
        self.episodes = 0

    def fails(self, plan: list[Fault]) -> bool:
        spec = format_plan(plan)
        hit = self.cache.get(spec)
        if hit is not None:
            return hit
        self.episodes += 1
        res = run_episode(dataclasses.replace(self.cfg, plan=spec))
        checks = {v["check"] for v in res.violations}
        verdict = bool(checks) and checks <= self.allowed
        self.cache[spec] = verdict
        return verdict


def _ddmin(plan: list[Fault], fails) -> list[Fault]:
    """Zeller's ddmin over plan entries: probe complements of an
    n-granular partition, refining granularity until single-entry
    removals all pass — the standard locally-minimal guarantee."""
    n = 2
    while len(plan) >= 2:
        chunk = max(1, len(plan) // n)
        reduced = False
        for start in range(0, len(plan), chunk):
            candidate = plan[:start] + plan[start + chunk:]
            if candidate and fails(candidate):
                plan = candidate
                n = max(n - 1, 2)
                reduced = True
                break
        if reduced:
            continue
        if chunk == 1:
            break
        n = min(n * 2, len(plan))
    return plan


def _floor_at(site: str) -> int:
    """The smallest meaningful trigger per site class: fleet ticks
    start at 1 (a tick-0 fault fires before any dispatch exists);
    fleet.transport arms on the same tick counter; sequence-numbered
    sites start at 0 (the first handoff/spill)."""
    return 1 if site in ("fleet.tick", "fleet.transport") else 0


def _shrink_entry(plan: list[Fault], i: int, fails) -> list[Fault]:
    """Coordinate minimization for entry i: trigger tick first (floor,
    then repeated halving toward it), then droppable args, then numeric
    args toward their floors. Greedy, re-probing each move."""
    def attempt(f: Fault) -> bool:
        candidate = plan[:i] + [f] + plan[i + 1:]
        if fails(candidate):
            plan[i] = f
            return True
        return False

    f = plan[i]
    floor = _floor_at(f.site)
    # Trigger tick: try the floor outright, else binary-walk down.
    if f.at > floor and not attempt(dataclasses.replace(f, at=floor)):
        lo, hi = floor, plan[i].at
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if attempt(dataclasses.replace(plan[i], at=mid)):
                hi = mid
            else:
                lo = mid
    for key in _DROPPABLE:
        if key in plan[i].args:
            args = {k: v for k, v in plan[i].args.items() if k != key}
            attempt(dataclasses.replace(plan[i], args=args))
    for key, kfloor in _SHRINK_FLOORS.items():
        val = plan[i].args.get(key)
        if isinstance(val, int) and val > kfloor:
            args = dict(plan[i].args)
            args[key] = kfloor
            attempt(dataclasses.replace(plan[i], args=args))
    return plan


def shrink(cfg: EpisodeConfig) -> tuple[str, int]:
    """Minimize cfg.plan while the episode keeps failing the oracle
    with the same violation classes. Returns (minimal plan string,
    episodes probed). Raises ValueError if the starting episode does
    not fail — shrinking a passing plan is a caller bug."""
    first = run_episode(cfg)
    if first.ok:
        raise ValueError("shrink() on a passing episode: nothing to "
                         "minimize")
    allowed = {v["check"] for v in first.violations}
    prober = _Prober(cfg, allowed)
    prober.cache[cfg.plan] = True
    plan = parse_plan(cfg.plan)
    plan = _ddmin(plan, prober.fails)
    for i in range(len(plan)):
        plan = _shrink_entry(plan, i, prober.fails)
    return format_plan(plan), prober.episodes
