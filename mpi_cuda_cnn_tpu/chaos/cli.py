"""`mctpu chaos` — the seeded fault-schedule search driver.

Default mode samples N (axes, plan) episodes, runs each through the
episode harness + global invariant oracle, and folds a deterministic
episode CRC chain (the number the CI chaos gate pins at 0%/equal
across two identical invocations). On any violation the run keeps
going (the chain must stay comparable), then shrinks the FIRST
violating episode to a minimal plan, writes both trails of the
minimal episode for `mctpu diverge`, prints the one-line repro, and
exits 1.

`--plan` mode replays exactly one episode from a plan spelling — the
repro form the failure path prints — with the axes set by flags
instead of sampled.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import zlib
from pathlib import Path

from ..faults import parse_plan, validate_plan_sites
from ..obs.schema import RUN_MARKER, make_record, validate_record
from .episode import EpisodeConfig, EpisodeResult, config_for, run_episode
from .sampler import SURFACE, EpisodeAxes, sample_axes, sample_plan
from .shrink import shrink


def _episode_rng(seed: int, ep: int) -> random.Random:
    """One independent, platform-stable stream per episode: string
    seeding hashes the bytes (not PYTHONHASHSEED), so episode k of
    seed s samples identically everywhere, forever."""
    return random.Random(f"mctpu-chaos:{seed}:{ep}")


def _write_trail(path: Path, records: list[dict]) -> None:
    with path.open("w") as fh:
        fh.write(f"{RUN_MARKER} mctpu chaos\n")
        for rec in records:
            fh.write(json.dumps(rec) + "\n")


def _emit(path: str, rows: list[dict]) -> None:
    """Append schema-stamped chaos records (the autosize emit shape):
    one run segment per invocation, t = the episode ordinal — the
    chaos timeline is episode-indexed, never wall-clock."""
    with open(path, "a") as fh:
        fh.write(f"{RUN_MARKER} mctpu chaos\n")
        for i, row in enumerate(rows):
            rec = validate_record(make_record("chaos", float(i), **row))
            fh.write(json.dumps(rec) + "\n")


def _fail_report(args, ep: int, res: EpisodeResult) -> tuple[str, dict]:
    """Shrink the violating episode, write the minimal episode's twin
    trails, print the repro block. Returns (min_plan, extra summary
    fields)."""
    cfg = res.config
    checks = sorted({v["check"] for v in res.violations})
    for v in res.violations:
        print(f"  {v['check']}: {v['detail']}")
    min_plan, probes = cfg.plan, 0
    if cfg.plan and not args.no_shrink:
        min_plan, probes = shrink(cfg)
        print(f"shrunk: {len(parse_plan(cfg.plan))} fault(s) -> "
              f"{len(parse_plan(min_plan))} over {probes} probe "
              f"episode(s)")
    extra = {"failed_episode": ep, "min_plan": min_plan,
             "shrink_probes": probes}
    min_res = run_episode(EpisodeConfig(
        **{**cfg.__dict__, "plan": min_plan}))
    if args.out_dir:
        out = Path(args.out_dir)
        out.mkdir(parents=True, exist_ok=True)
        a, b = out / "chaos_min_a.jsonl", out / "chaos_min_b.jsonl"
        _write_trail(a, min_res.records_a)
        _write_trail(b, min_res.records_b)
        print(f"trails: {a} {b}\n"
              f"diverge: python -m mpi_cuda_cnn_tpu diverge {a} {b}")
    axes_flags = []
    if cfg.pools:
        axes_flags.append(f"--pools {cfg.pools}")
    if cfg.prefix:
        axes_flags.append("--prefix")
    if cfg.spill:
        axes_flags.append("--spill")
    if cfg.spec != "off":
        axes_flags.append(f"--spec {cfg.spec}")
    if cfg.autoscale:
        axes_flags.append("--autoscale")
    if cfg.transport:
        axes_flags.append("--transport")
    if cfg.plant:
        axes_flags.append(f"--plant {cfg.plant}")
    flags = (" " + " ".join(axes_flags)) if axes_flags else ""
    print(f"minimal plan ({', '.join(checks)}): {min_plan}\n"
          f"repro: python -m mpi_cuda_cnn_tpu chaos --seed {cfg.seed}"
          f" --requests {cfg.requests} --replicas {cfg.replicas}{flags}"
          f" --plan '{min_plan}'")
    return min_plan, extra


def chaos_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="mctpu chaos",
        description="Seeded fault-schedule search over the fleet storm "
                    "with a global invariant oracle and automatic ddmin "
                    "plan minimization (ISSUE 19).",
    )
    ap.add_argument("--episodes", type=int, default=20,
                    help="sampled (axes, plan) episodes to run")
    ap.add_argument("--seed", type=int, default=0,
                    help="master seed: episode k samples from the "
                         "independent stream (seed, k)")
    ap.add_argument("--requests", type=int, default=32,
                    help="workload size per episode (tier-1 scale)")
    ap.add_argument("--replicas", type=int, default=3,
                    help="initial replicas (unified episodes; pooled "
                         "episodes size from the sampled split)")
    ap.add_argument("--max-tick", type=int, default=96,
                    help="latest fleet.tick trigger the sampler draws")
    ap.add_argument("--plan", default=None,
                    help="replay ONE episode from this --fault-plan "
                         "spelling instead of sampling (the repro form "
                         "a failing search prints); axes come from the "
                         "flags below")
    ap.add_argument("--pools", default=None,
                    help="disaggregated split for --plan mode "
                         "(fleet-bench grammar: prefill:P,decode:D)")
    ap.add_argument("--prefix", action="store_true",
                    help="prefix cache on (--plan mode)")
    ap.add_argument("--spill", action="store_true",
                    help="host-tier spill on; needs --prefix "
                         "(--plan mode)")
    ap.add_argument("--spec", default="off", choices=("off", "lookup"),
                    help="speculative decoding (--plan mode)")
    ap.add_argument("--autoscale", action="store_true",
                    help="online autoscaler on (--plan mode)")
    ap.add_argument("--transport", action="store_true",
                    help="lossy transport bus + lease fences on; "
                         "required for fleet.transport plan entries "
                         "(--plan mode)")
    ap.add_argument("--no-shrink", action="store_true",
                    help="report the raw violating plan without ddmin "
                         "minimization")
    ap.add_argument("--out-dir", default=None,
                    help="directory for the minimal episode's twin "
                         "trails on failure (pre-wired for `mctpu "
                         "diverge`)")
    ap.add_argument("--metrics-jsonl", default=None,
                    help="append one chaos record per episode plus the "
                         "run summary (obs schema; the CI chaos gate "
                         "compares these)")
    ap.add_argument("--plant", default=None,
                    choices=("skip-revoke", "skip-dedup"),
                    help="TEST-ONLY: arm a planted invariant bug "
                         "(serve/fleet.CHAOS_PLANT) the search must "
                         "find and shrink — skip-revoke drops a fence "
                         "revoke on failover, skip-dedup disables the "
                         "bus's commit dedup check (ISSUE 20); the "
                         "oracle's own canary, never for real runs")
    args = ap.parse_args(argv)
    if args.spill and not args.prefix:
        print("error: --spill needs --prefix (the host tier spills "
              "prefix-tree pages)", file=sys.stderr)
        return 2

    if args.plan is not None:
        try:
            validate_plan_sites(parse_plan(args.plan), SURFACE)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        axes = EpisodeAxes(pools=args.pools, prefix=args.prefix,
                           spill=args.spill, spec=args.spec,
                           autoscale=args.autoscale,
                           transport=args.transport)
        episodes = [(args.plan, axes)]
    else:
        episodes = []
        for ep in range(args.episodes):
            rng = _episode_rng(args.seed, ep)
            axes = sample_axes(rng)
            n_replicas = (sum(int(p.rsplit(":", 1)[1])
                              for p in axes.pools.split(","))
                          if axes.pools else args.replicas)
            episodes.append((sample_plan(rng, axes, replicas=n_replicas,
                                         max_tick=args.max_tick), axes))

    rows: list[dict] = []
    chain = 0
    first_fail: tuple[int, EpisodeResult] | None = None
    for ep, (plan, axes) in enumerate(episodes):
        # Sampled episodes decorrelate workloads per ordinal; --plan
        # mode replays the EXACT seed the failure report printed.
        ep_seed = (args.seed if args.plan is not None
                   else args.seed * 100003 + ep)
        cfg = config_for(ep_seed, plan, axes,
                         requests=args.requests, replicas=args.replicas,
                         plant=args.plant)
        res = run_episode(cfg)
        chain = zlib.crc32(res.crc.to_bytes(4, "little"), chain)
        rows.append({**res.row, "episode": ep, "axes": axes.label()})
        verdict = ("ok" if res.ok
                   else "FAIL " + ",".join(sorted({v["check"]
                                                   for v in res.violations})))
        print(f"episode {ep:3d} [{axes.label()}] {plan or '(no faults)'}"
              f" -> {verdict}")
        if not res.ok and first_fail is None:
            first_fail = (ep, res)
    failed = [r["episode"] for r in rows if r["violations"]]
    summary = {
        "kind": "summary", "episodes": len(rows),
        "violations": len(failed), "failed": failed,
        "episodes_crc": chain,
    }
    if first_fail is not None:
        _, extra = _fail_report(args, *first_fail)
        summary.update(extra)
    if args.metrics_jsonl:
        _emit(args.metrics_jsonl, rows + [summary])
    print(f"chaos: {len(rows)} episode(s), {len(failed)} violating, "
          f"episodes_crc {chain}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(chaos_main())
