"""One chaos episode: a sampled fault plan through the real fleet
storm, then the global invariant oracle.

The episode harness is deliberately the SAME fleet construction
`mctpu fleet-bench` and `mctpu autosize` use (SimCompute, FakeClock,
identical defaults), so every sampled schedule is a one-line
``mctpu fleet-bench --fault-plan '<plan>'`` repro and the storm's
trace/state/blame CRCs mean the same thing they mean everywhere else.
What chaos adds is the oracle: a declarative correctness spec (the
FATE & DESTINI shape — Gunawi et al., NSDI'11) checked after EVERY
episode, not a per-feature assertion checked where an author thought
to look:

1. every request terminal exactly once (statuses AND the trail's
   fence-accepted terminal stream agree — no loss, no double count);
2. finished outputs equal the SimCompute closed form, and every
   committed token matches it (no double generation, no zombie leak);
3. blame conservation, bitwise (obs.causal: per-request categories sum
   exactly to the end-to-end span);
4. PagePool.check() + host-tier accounting clean at exit (the fleet
   run itself raises on a pool violation — the harness converts any
   raise into a violation instead of dying);
5. `mctpu replay` zero-drift on the in-memory trail (the event-sourced
   mirror re-derives every state digest);
6. same-(seed, plan) re-run bitwise: trace/state/blame CRCs equal
   across two independent runs.

Each episode runs the plan TWICE — check 6 needs the twin, and the
pair of trails is exactly what `mctpu diverge` wants when a violation
survives shrinking.
"""

from __future__ import annotations

import dataclasses
import json
import zlib

from ..faults import parse_plan

# Statuses a request may legally end in (serve/scheduler.py contract).
TERMINAL_STATUSES = frozenset(
    {"finished", "expired", "cancelled", "rejected", "failed"})


@dataclasses.dataclass(frozen=True)
class EpisodeConfig:
    """One episode's full recipe: (seed, plan) plus the sampled axes
    and the tier-1 scale knobs. Frozen and hashable on purpose — the
    shrinker re-runs `dataclasses.replace(cfg, plan=...)` variants and
    caches verdicts by spelling."""

    seed: int
    plan: str = ""
    replicas: int = 3
    pools: str | None = None
    prefix: bool = False
    spill: bool = False
    spec: str = "off"
    autoscale: bool = False
    transport: bool = False
    requests: int = 32
    rate: float = 48.0
    vocab: int = 64
    prompt_min: int = 4
    prompt_max: int = 40
    out_min: int = 4
    out_max: int = 16
    slots: int = 4
    page_size: int = 16
    tick_ms: float = 2.0
    # Test-only fault SEED (ISSUE 19 satellite): names a planted
    # invariant bug in serve/fleet.py (CHAOS_PLANT) the oracle must
    # catch. Never set outside tests / `mctpu chaos --plant`.
    plant: str | None = None

    @property
    def n_replicas(self) -> int:
        if self.pools:
            # The --pools grammar: "prefill:P,decode:D" (serve.handoff
            # .parse_pools); replica count is the phase sum.
            return sum(int(part.rsplit(":", 1)[1])
                       for part in self.pools.split(","))
        return self.replicas


def config_for(seed: int, plan: str, axes, **scale) -> EpisodeConfig:
    """Fold sampled axes + a sampled plan into one EpisodeConfig."""
    return EpisodeConfig(
        seed=seed, plan=plan, pools=axes.pools, prefix=axes.prefix,
        spill=axes.spill, spec=axes.spec, autoscale=axes.autoscale,
        transport=axes.transport, **scale,
    )


@dataclasses.dataclass
class EpisodeResult:
    config: EpisodeConfig
    violations: list[dict]
    crc: int
    row: dict
    records_a: list[dict]
    records_b: list[dict]

    @property
    def ok(self) -> bool:
        return not self.violations


def _crc(obj) -> int:
    return zlib.crc32(json.dumps(obj, sort_keys=True).encode())


def _run_once(cfg: EpisodeConfig, records: list[dict]) -> dict:
    """One storm; `records` fills with the replayable trail (the same
    event spellings fleet-bench writes to JSONL) even when the run
    raises mid-way — a partial trail is still forensic material."""
    from ..faults import FakeClock, FaultInjector
    from ..obs.causal import BlameAccumulator
    from ..obs.metrics import MetricsRegistry
    # The one sanctioned non-jax-free import: serve/fleet.py is
    # transitively jax-free on the SimCompute path (EngineCompute's
    # engine import is lazy) but hosts the engine-compute factory too,
    # so it stays outside the manifest; the sim-only use here is the
    # same deliberate exception obs/autosize.py documents.
    from ..serve import fleet as fleet_mod  # mctpu: disable=MCT001
    from ..serve.pool import pages_for

    max_len = cfg.prompt_max + cfg.out_max
    pages = cfg.slots * pages_for(max_len, cfg.page_size) + 1
    host_pages = pages if cfg.spill else 0
    reqs = fleet_mod.make_fleet_workload(
        n=cfg.requests, vocab=cfg.vocab, prompt_min=cfg.prompt_min,
        prompt_max=cfg.prompt_max, out_min=cfg.out_min,
        out_max=cfg.out_max, rate=cfg.rate, seed=cfg.seed,
    )
    clock = FakeClock()
    registry = MetricsRegistry(clock=clock)
    blame = BlameAccumulator()

    def fleet_sink(rec: dict) -> None:
        blame.ingest_fleet(rec)
        records.append({"event": "fleet", **rec})

    def tick_sink(rec: dict) -> None:
        blame.ingest_tick(rec)
        records.append({"event": "tick", **rec})

    autoscaler = None
    if cfg.autoscale:
        from ..serve.autoscale import Autoscaler, parse_autoscale

        autoscaler = Autoscaler(parse_autoscale("on"))
    fleet = fleet_mod.Fleet(
        lambda name: fleet_mod.SimCompute(vocab=cfg.vocab, chunk=16,
                                          salt=cfg.seed),
        replicas=cfg.replicas, slots=cfg.slots, num_pages=pages,
        page_size=cfg.page_size, max_len=max_len,
        policy="least_loaded", heartbeat_miss=3, backoff_base=0.05,
        max_flaps=3, redispatch="resume", tick_s=cfg.tick_ms / 1e3,
        check_every=16,
        faults=FaultInjector(cfg.plan) if cfg.plan else None,
        clock=clock, registry=registry,
        fleet_sink=fleet_sink, replica_tick_sink=tick_sink,
        prefix=cfg.prefix, spec=cfg.spec, spec_k=8, spec_ngram=2,
        pools=cfg.pools, handoff_ticks=1, log_handoffs=False,
        host_pages=host_pages, autoscale=autoscaler,
        transport=cfg.transport,
    )
    # The planted bug (test-only): flipped around the run alone so a
    # raise can never leak the toggle into the next episode.
    fleet_mod.CHAOS_PLANT = cfg.plant
    try:
        result = fleet.run(reqs)
    finally:
        fleet_mod.CHAOS_PLANT = None
    for rec in result.replica_log:
        records.append({"event": "replica", **rec})
    for rec in result.request_records():
        records.append({"event": "request", **rec})
    s = result.summary()
    # The run-geometry record the replay mirror reconstructs from —
    # the same spelling fleet_bench_main stamps (mode comes from **s).
    records.append({
        "event": "serve", "bench": "fleet", "policy": "least_loaded",
        "autoscale": cfg.autoscale, "redispatch": "resume",
        "spec": cfg.spec, "spec_k": 8, "replicas_initial": cfg.n_replicas,
        "rate": cfg.rate, "slots": cfg.slots, "page_size": cfg.page_size,
        "pages": pages, "compute": "sim", "prefix_cache": cfg.prefix,
        "host_pages": host_pages, "transport": cfg.transport,
        "lease_ticks": fleet.lease_ticks, **s,
    })
    for rec in result.transport_log:
        records.append({"event": "transport", **rec})
    return {"result": result, "fleet": fleet, "summary": s,
            "blame": blame, "sim": fleet_mod.SimCompute(
                vocab=cfg.vocab, chunk=16, salt=cfg.seed),
        "host_pages": host_pages}


def _check_requests(cfg: EpisodeConfig, run: dict,
                    violations: list[dict]) -> None:
    """Oracle checks 1+2: terminal-exactly-once and the closed form."""
    result, sim = run["result"], run["sim"]
    if len(result.requests) != cfg.requests:
        violations.append({
            "check": "terminal",
            "detail": f"{len(result.requests)} requests in the result, "
                      f"workload had {cfg.requests}"})
    for r in sorted(result.requests, key=lambda r: r.rid):
        if r.status not in TERMINAL_STATUSES:
            violations.append({
                "check": "terminal",
                "detail": f"rid {r.rid} ended non-terminal: {r.status!r}"})
            continue
        if r.status == "finished" and len(r.out) != r.max_new_tokens:
            violations.append({
                "check": "outputs",
                "detail": f"rid {r.rid} finished with {len(r.out)} "
                          f"tokens, budget {r.max_new_tokens}"})
        bad = next((j for j, tok in enumerate(r.out)
                    if tok != sim._tok_at(r, j)), None)
        if bad is not None:
            violations.append({
                "check": "outputs",
                "detail": f"rid {r.rid} token {bad} diverges from the "
                          "SimCompute closed form (lost/duplicated or "
                          "zombie-committed generation)"})


def _check_terminal_stream(cfg: EpisodeConfig, records: list[dict],
                           violations: list[dict]) -> None:
    """Check 1, trail half: the fence-accepted terminal stream must
    name every rid exactly once — a request terminal in the result but
    absent (or doubled) in the stream is a lost/duplicated SLO event."""
    seen: dict[int, int] = {}
    for rec in records:
        if rec.get("event") == "tick":
            stream = rec.get("terminal") or ()
        elif rec.get("event") == "fleet":
            # Deferred terminals applied at bus pump (ISSUE 20) ride
            # the fleet record's t_terminal stream ONLY — they never
            # reach a replica tick record — so exactly-once is over
            # the union of both streams.
            stream = rec.get("t_terminal") or ()
        else:
            continue
        for t in stream:
            rid = t.get("id")
            seen[rid] = seen.get(rid, 0) + 1
    dup = sorted(rid for rid, n in seen.items() if n > 1)
    if dup:
        violations.append({
            "check": "terminal",
            "detail": f"rid(s) {dup} terminal more than once in the "
                      "trail's fence-accepted stream"})
    if len(seen) != cfg.requests:
        violations.append({
            "check": "terminal",
            "detail": f"trail carries {len(seen)} terminal rids, "
                      f"workload had {cfg.requests}"})


def _check_blame(run: dict, violations: list[dict]) -> None:
    """Check 3: bitwise blame conservation (obs.causal)."""
    for problem in run["blame"].check("fleet"):
        violations.append({"check": "blame", "detail": problem})


def _check_pools(cfg: EpisodeConfig, run: dict,
                 violations: list[dict]) -> None:
    """Check 4, tier half: Fleet.run already re-checks every surviving
    PagePool at exit (a violation raises and lands as an `exception`
    violation); what it does not assert is host-tier occupancy staying
    inside its bound."""
    for member in run["fleet"].router.members.values():
        tier = member.replica.core.tier
        if tier is not None and tier.host_used > run["host_pages"]:
            violations.append({
                "check": "pool",
                "detail": f"{member.name} host tier holds "
                          f"{tier.host_used} pages, bound "
                          f"{run['host_pages']}"})


def _check_replay(records: list[dict], violations: list[dict]) -> int:
    """Check 5: fold the event-sourced mirror over the trail and
    cross-check every stamped state digest. Returns ticks checked."""
    from ..obs.replay import DriftError, ReplayError, RunReplay

    try:
        replay = RunReplay(records)
        replay.fold()
        return replay.ticks_checked
    except (DriftError, ReplayError) as e:
        violations.append({"check": "replay",
                           "detail": f"{type(e).__name__}: {e}"})
        return 0


def run_episode(cfg: EpisodeConfig) -> EpisodeResult:
    """Run (seed, plan) twice, check the full oracle, fold the episode
    CRC. Violations carry {"check", "detail"}; an empty list is a pass."""
    violations: list[dict] = []
    records_a: list[dict] = []
    records_b: list[dict] = []
    runs, errors = [], []
    for records in (records_a, records_b):
        try:
            runs.append(_run_once(cfg, records))
            errors.append(None)
        except Exception as e:  # noqa: BLE001 — the oracle reports, never dies
            runs.append(None)
            errors.append(f"{type(e).__name__}: {e}")
    a, b = runs
    crcs = statuses = None
    replay_ticks = 0
    if errors[0]:
        violations.append({"check": "exception", "detail": errors[0]})
    if a is not None:
        _check_requests(cfg, a, violations)
        _check_terminal_stream(cfg, records_a, violations)
        _check_blame(a, violations)
        _check_pools(cfg, a, violations)
        replay_ticks = _check_replay(records_a, violations)
        bf = a["blame"].summary_fields("fleet")
        crcs = {"trace_crc": a["summary"]["trace_crc"],
                "state_crc": a["summary"]["state_crc"],
                "blame_crc": bf["crc"]}
        statuses = a["summary"]["statuses"]
    # Check 6: the deterministic twin. With both runs dead, the raise
    # itself must at least be deterministic.
    if a is not None and b is not None:
        twin = {"trace_crc": b["summary"]["trace_crc"],
                "state_crc": b["summary"]["state_crc"],
                "blame_crc": b["blame"].summary_fields("fleet")["crc"]}
        if twin != crcs:
            violations.append({
                "check": "determinism",
                "detail": f"same-(seed, plan) re-run diverged: {crcs} "
                          f"vs {twin}"})
    elif (a is None) != (b is None) or errors[0] != errors[1]:
        violations.append({
            "check": "determinism",
            "detail": f"re-run outcome diverged: {errors[0]!r} vs "
                      f"{errors[1]!r}"})
    crc = _crc({
        "seed": cfg.seed, "plan": cfg.plan, "pools": cfg.pools,
        "prefix": cfg.prefix, "spill": cfg.spill, "spec": cfg.spec,
        "autoscale": cfg.autoscale, "transport": cfg.transport,
        "statuses": statuses,
        "violations": sorted({v["check"] for v in violations}), **(crcs or {}),
    })
    row = {
        "kind": "episode", "seed": cfg.seed, "plan": cfg.plan,
        "faults": len(parse_plan(cfg.plan)) if cfg.plan else 0,
        "requests": cfg.requests,
        "violations": sorted({v["check"] for v in violations}),
        "replay_ticks": replay_ticks, "episode_crc": crc,
        **(crcs or {}),
    }
    return EpisodeResult(config=cfg, violations=violations, crc=crc,
                         row=row, records_a=records_a,
                         records_b=records_b)
