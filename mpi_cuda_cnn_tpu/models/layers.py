"""Functional layer API.

The reference's model is a doubly-linked list of `Layer` structs carrying
their own buffers (cnn.c:15-43), with three layer kinds: input, conv, full
(cnn.c:8-12). Here a model is data (a tuple of stateless layer descriptors)
plus a params pytree; apply is a pure function so it composes with jit,
grad, vmap, shard_map and checkpointing. Pooling layers are added beyond
the reference (it downsamples only via stride-2 conv, SURVEY.md 2.10) since
the benchmark presets (LeNet-5, VGG) need them.

Each layer implements:
    init(key, in_shape, initializer, dtype) -> (params, out_shape)
    apply(params, x, backend) -> y
with in/out shapes per-sample (H, W, C) or (features,); apply operates on
batched arrays (N, ...). `backend` selects "xla" oracle ops or the Pallas
TPU kernels (ops/pallas_ops.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ..ops import conv2d, dense
from ..ops.activations import ACTIVATIONS

Params = Any


def _apply_activation(name: str | None, x: jnp.ndarray) -> jnp.ndarray:
    return ACTIVATIONS[name](x)


@dataclasses.dataclass(frozen=True)
class Conv:
    """2-D convolution + bias + activation.

    Twin of the reference conv layer (Layer_create_conv cnn.c:328-343,
    forward cnn.c:175-210): square kernel, uniform stride/padding, ReLU
    fused into the forward. NHWC/HWIO layouts for the TPU.
    """

    features: int
    kernel: int = 3
    stride: int = 1
    padding: int = 0
    activation: str | None = "relu"

    def init(self, key, in_shape, initializer, dtype=jnp.float32):
        h, w, c = in_shape
        params = {
            "w": initializer(key, (self.kernel, self.kernel, c, self.features), dtype),
            "b": jnp.zeros((self.features,), dtype),
        }
        oh = (h + 2 * self.padding - self.kernel) // self.stride + 1
        ow = (w + 2 * self.padding - self.kernel) // self.stride + 1
        return params, (oh, ow, self.features)

    def apply(self, params, x, backend="xla"):
        if backend == "pallas":
            from ..ops.pallas_ops import conv2d_pallas

            y = conv2d_pallas(
                x, params["w"], stride=self.stride, padding=self.padding
            ) + params["b"]
        else:
            y = conv2d(x, params["w"], stride=self.stride, padding=self.padding)
            y = y + params["b"]
        return _apply_activation(self.activation, y)


@dataclasses.dataclass(frozen=True)
class Dense:
    """Fully-connected + bias + activation (Layer_create_full cnn.c:318-326,
    forward cnn.c:113-152). Accepts (N, d) or unflattened (N, H, W, C) input
    — the reference's FC layers read the conv buffer flat the same way."""

    features: int
    activation: str | None = "tanh"

    def init(self, key, in_shape, initializer, dtype=jnp.float32):
        d_in = int(jnp.prod(jnp.array(in_shape)))
        params = {
            "w": initializer(key, (d_in, self.features), dtype),
            "b": jnp.zeros((self.features,), dtype),
        }
        return params, (self.features,)

    def apply(self, params, x, backend="xla"):
        x = x.reshape(x.shape[0], -1)
        if backend == "pallas":
            from ..ops.pallas_ops import dense_pallas

            y = dense_pallas(x, params["w"], params["b"])
        else:
            y = dense(x, params["w"], params["b"])
        return _apply_activation(self.activation, y)


def _pool(x: jnp.ndarray, window: int, stride: int, kind: str) -> jnp.ndarray:
    """Pooling over NHWC. Non-overlapping windows (stride == window, the only
    form the presets use) lower to a reshape + reduce, which XLA vectorizes
    on the VPU and which differentiates cleanly under shard_map; overlapping
    windows fall back to reduce_window."""
    n, h, w, c = x.shape
    if stride == window and h % window == 0 and w % window == 0:
        r = x.reshape(n, h // window, window, w // window, window, c)
        return r.max(axis=(2, 4)) if kind == "max" else r.mean(axis=(2, 4))
    init = -jnp.inf if kind == "max" else 0.0
    op = jax.lax.max if kind == "max" else jax.lax.add
    out = jax.lax.reduce_window(
        x,
        jnp.array(init, x.dtype),
        op,
        (1, window, window, 1),
        (1, stride, stride, 1),
        "VALID",
    )
    return out if kind == "max" else out / (window * window)


@dataclasses.dataclass(frozen=True)
class MaxPool:
    """Max pooling. Not present in the reference (SURVEY.md 2.10: stride-2
    conv is its only downsampler) but required by the LeNet-5/VGG presets
    named in the north star (BASELINE.json)."""

    window: int = 2
    stride: int | None = None

    def init(self, key, in_shape, initializer, dtype=jnp.float32):
        s = self.stride or self.window
        h, w, c = in_shape
        return {}, ((h - self.window) // s + 1, (w - self.window) // s + 1, c)

    def apply(self, params, x, backend="xla"):
        s = self.stride or self.window
        return _pool(x, self.window, s, "max")


@dataclasses.dataclass(frozen=True)
class AvgPool:
    """Average pooling (classic LeNet-5 subsampling)."""

    window: int = 2
    stride: int | None = None

    def init(self, key, in_shape, initializer, dtype=jnp.float32):
        s = self.stride or self.window
        h, w, c = in_shape
        return {}, ((h - self.window) // s + 1, (w - self.window) // s + 1, c)

    def apply(self, params, x, backend="xla"):
        s = self.stride or self.window
        return _pool(x, self.window, s, "avg")


@dataclasses.dataclass(frozen=True)
class Flatten:
    def init(self, key, in_shape, initializer, dtype=jnp.float32):
        return {}, (int(jnp.prod(jnp.array(in_shape))),)

    def apply(self, params, x, backend="xla"):
        return x.reshape(x.shape[0], -1)


@dataclasses.dataclass(frozen=True)
class Residual:
    """Residual block: y = act(body(x) + shortcut(x)).

    Beyond the reference (its model topology is a doubly-linked list,
    cnn.c:15-43, which can only express straight-line stacks); included so
    the preset registry covers a modern conv family. The shortcut is the
    identity when the body preserves shape, otherwise a 1x1 strided
    projection conv (He et al. option B). The body's last layer should have
    activation=None — the block activation applies after the add.
    """

    body: tuple
    activation: str | None = "relu"

    def init(self, key, in_shape, initializer, dtype=jnp.float32):
        keys = jax.random.split(key, len(self.body) + 1)
        body_params = []
        shape = in_shape
        for layer, k in zip(self.body, keys[:-1]):
            p, shape = layer.init(k, shape, initializer, dtype)
            body_params.append(p)
        params: dict[str, Any] = {"body": body_params}
        if shape != in_shape:
            stride = self._proj_stride(in_shape, shape)
            proj = Conv(shape[-1], kernel=1, stride=stride, padding=0,
                        activation=None)
            params["proj"], _ = proj.init(keys[-1], in_shape, initializer, dtype)
        return params, shape

    @staticmethod
    def _proj_stride(in_shape, out_shape) -> int:
        """Stride s such that a 1x1 VALID conv maps (h,w) -> (oh,ow), i.e.
        (h-1)//s+1 == oh for both dims; odd dims (7 -> 4 at s=2) included."""
        h, w, _ = in_shape
        oh, ow, _ = out_shape
        for s in range(1, h + 1):
            if (h - 1) // s + 1 == oh and (w - 1) // s + 1 == ow:
                return s
        raise ValueError(
            f"Residual body maps {in_shape} -> {out_shape}, which a 1x1 "
            "strided projection cannot match"
        )

    def apply(self, params, x, backend="xla"):
        y = x
        for layer, p in zip(self.body, params["body"]):
            y = layer.apply(p, y, backend=backend)
        if "proj" in params:
            stride = self._proj_stride(x.shape[1:], y.shape[1:])
            proj = Conv(y.shape[-1], kernel=1, stride=stride, padding=0,
                        activation=None)
            x = proj.apply(params["proj"], x, backend=backend)
        return _apply_activation(self.activation, y + x)


@dataclasses.dataclass(frozen=True)
class GlobalAvgPool:
    """Spatial global average -> (N, C). Standard ResNet head."""

    def init(self, key, in_shape, initializer, dtype=jnp.float32):
        return {}, (in_shape[-1],)

    def apply(self, params, x, backend="xla"):
        return x.mean(axis=(1, 2))


@dataclasses.dataclass(frozen=True)
class Sequential:
    """A feed-forward stack — the functional twin of the reference's linked
    list walked by Layer_setInputs (forward, cnn.c:249-268) and
    Layer_learnOutputs (backward via jax.grad, cnn.c:284-301).

    The final Dense's activation should be None: the softmax lives in the
    loss (softmax_cross_entropy), exactly equivalent to the reference's
    softmax-forward + error-seeding split (SURVEY.md 2.5).
    """

    layers: tuple
    input_shape: tuple[int, ...]
    name: str = "model"

    def init(self, key, initializer, dtype=jnp.float32) -> list[Params]:
        params = []
        shape = self.input_shape
        keys = jax.random.split(key, len(self.layers))
        for layer, k in zip(self.layers, keys):
            p, shape = layer.init(k, shape, initializer, dtype)
            params.append(p)
        return params

    def apply(self, params: list[Params], x: jnp.ndarray, *,
              backend: str = "xla", compute_dtype=None,
              remat: bool = False) -> jnp.ndarray:
        """x: (N, H, W, C) -> logits (N, num_classes).

        compute_dtype=bfloat16 casts activations (params are cast per-op by
        XLA's dot/conv mixed-precision) so matmuls hit the MXU's native
        bf16 path; logits are returned in f32 for the loss.

        remat=True wraps each layer in jax.checkpoint: the backward pass
        recomputes that layer's activations instead of keeping them live —
        FLOPs traded for HBM (a lever the reference, which stores every
        layer's outputs/errors permanently in the Layer struct, cnn.c:22-30,
        does not have).
        """
        if compute_dtype is not None:
            x = x.astype(compute_dtype)
            params = jax.tree.map(lambda p: p.astype(compute_dtype), params)
        for layer, p in zip(self.layers, params):
            f = (lambda p_, x_, _l=layer: _l.apply(p_, x_, backend=backend))
            if remat:
                f = jax.checkpoint(f)
            x = f(p, x)
        return x.astype(jnp.float32)

    def num_params(self, params: list[Params]) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(params))
