"""Model presets — the five benchmark configurations (BASELINE.json).

- reference_cnn: the reference's hardcoded net (cnn.c:416-428): 1x28x28 ->
  conv16 k3 s2 p1 (relu) -> conv32 k3 s2 p1 (relu) -> fc200 tanh -> fc200
  tanh -> fc10 softmax. 360,810 params (SURVEY.md 2.10) — the parity target.
- lenet5: classic LeCun-98 LeNet-5 (tanh + avg-pool), 28x28 padded to 32.
- lenet5_relu: modernized LeNet-5 (relu + max-pool) — the ≥99%-accuracy
  route (SURVEY.md §7 hard-part (f)).
- cifar3conv: the 3-conv-layer CIFAR-10 config.
- vgg_small: VGG-style conv blocks on CIFAR-10 (stress conv kernels).
- resnet8: small CIFAR-10 ResNet — beyond BASELINE.json; exercises the
  non-sequential (Residual) topology path.
"""

from __future__ import annotations

from .layers import (
    AvgPool,
    Conv,
    Dense,
    Flatten,
    GlobalAvgPool,
    MaxPool,
    Residual,
    Sequential,
)

MNIST_SHAPE = (28, 28, 1)
CIFAR_SHAPE = (32, 32, 3)


def reference_cnn() -> Sequential:
    return Sequential(
        name="reference_cnn",
        input_shape=MNIST_SHAPE,
        layers=(
            Conv(16, kernel=3, stride=2, padding=1, activation="relu"),
            Conv(32, kernel=3, stride=2, padding=1, activation="relu"),
            Dense(200, activation="tanh"),
            Dense(200, activation="tanh"),
            Dense(10, activation=None),
        ),
    )


def lenet5() -> Sequential:
    return Sequential(
        name="lenet5",
        input_shape=MNIST_SHAPE,
        layers=(
            Conv(6, kernel=5, padding=2, activation="tanh"),
            AvgPool(2),
            Conv(16, kernel=5, padding=0, activation="tanh"),
            AvgPool(2),
            Flatten(),
            Dense(120, activation="tanh"),
            Dense(84, activation="tanh"),
            Dense(10, activation=None),
        ),
    )


def lenet5_relu() -> Sequential:
    return Sequential(
        name="lenet5_relu",
        input_shape=MNIST_SHAPE,
        layers=(
            Conv(32, kernel=5, padding=2, activation="relu"),
            MaxPool(2),
            Conv(64, kernel=5, padding=0, activation="relu"),
            MaxPool(2),
            Flatten(),
            Dense(256, activation="relu"),
            Dense(128, activation="relu"),
            Dense(10, activation=None),
        ),
    )


def cifar3conv() -> Sequential:
    return Sequential(
        name="cifar3conv",
        input_shape=CIFAR_SHAPE,
        layers=(
            Conv(32, kernel=3, padding=1, activation="relu"),
            MaxPool(2),
            Conv(64, kernel=3, padding=1, activation="relu"),
            MaxPool(2),
            Conv(128, kernel=3, padding=1, activation="relu"),
            MaxPool(2),
            Flatten(),
            Dense(256, activation="relu"),
            Dense(10, activation=None),
        ),
    )


def vgg_small() -> Sequential:
    def block(c):
        return (
            Conv(c, kernel=3, padding=1, activation="relu"),
            Conv(c, kernel=3, padding=1, activation="relu"),
            MaxPool(2),
        )

    return Sequential(
        name="vgg_small",
        input_shape=CIFAR_SHAPE,
        layers=(
            *block(64),
            *block(128),
            *block(256),
            Flatten(),
            Dense(512, activation="relu"),
            Dense(10, activation=None),
        ),
    )


def resnet8() -> Sequential:
    """8-layer CIFAR-10 ResNet (3 residual stages over a conv stem).

    A second conv model family beyond the reference's straight-line nets —
    exercises the non-sequential topology path (Residual/GlobalAvgPool).
    """

    def block(c, stride=1):
        return Residual(
            body=(
                Conv(c, kernel=3, stride=stride, padding=1, activation="relu"),
                Conv(c, kernel=3, stride=1, padding=1, activation=None),
            ),
        )

    return Sequential(
        name="resnet8",
        input_shape=CIFAR_SHAPE,
        layers=(
            Conv(16, kernel=3, padding=1, activation="relu"),
            block(16),
            block(32, stride=2),
            block(64, stride=2),
            GlobalAvgPool(),
            Dense(10, activation=None),
        ),
    )


MODEL_PRESETS = {
    "reference_cnn": reference_cnn,
    "lenet5": lenet5,
    "lenet5_relu": lenet5_relu,
    "cifar3conv": cifar3conv,
    "vgg_small": vgg_small,
    "resnet8": resnet8,
}


def get_model(name: str, input_shape: tuple[int, ...] | None = None) -> Sequential:
    if name not in MODEL_PRESETS:
        raise KeyError(f"unknown model {name!r}; known: {sorted(MODEL_PRESETS)}")
    model = MODEL_PRESETS[name]()
    if input_shape is not None and tuple(input_shape) != model.input_shape:
        model = Sequential(model.layers, tuple(input_shape), model.name)
    return model
