"""Minimal decoder-only transformer LM — the long-context model family.

The reference has no attention and no sequence axis (SURVEY.md §5.7); this
model exists to exercise the framework's long-context path end-to-end:
ring / Ulysses sequence parallelism (parallel/sp.py) under a real training
loop, not just as an op-level demo.

Design for SPMD: `apply` is written to run either as a plain global
program or INSIDE shard_map with the sequence dim sharded —

- token embedding, layernorm, and the MLP are per-position (shard-local);
- positions are explicit (`pos_offset`), so a sequence shard can compute
  its true absolute positions from its axis index;
- attention is pluggable (`attn_fn`): the full-attention oracle by
  default, ring/Ulysses bodies under shard_map.

Numerics: master params are f32; `compute_dtype=jnp.bfloat16` runs every
matmul (and the residual stream) in bf16 — the MXU's native path — with
layernorms and the softmax/loss still computed in f32. Pre-LN blocks;
learned position embeddings.
"""

from __future__ import annotations

import dataclasses
import math
from collections.abc import Callable

import jax
import jax.numpy as jnp

from ..ops.attention import attention, rope
from ..ops.pallas_gemv import QuantW, qmatmul


def _weight_cast(cd):
    """The compute-dtype weight cast, QuantW-aware: quantized decode
    weights (ops/pallas_gemv) carry their own storage dtype and must
    not be astype'd — qmatmul dequantizes them inside its kernel."""
    if cd is None:
        return lambda t: t
    return lambda t: t if isinstance(t, QuantW) else t.astype(cd)


def _layernorm(x, g, b, eps=1e-5):
    """Layernorm with the statistics in f32 regardless of x.dtype (bf16
    means/variances lose ~3 decimal digits); output back in x.dtype."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps) * g + b
    return y.astype(x.dtype)


@dataclasses.dataclass(frozen=True)
class TransformerLM:
    """Decoder-only LM: vocab -> dim, `depth` pre-LN blocks, tied LN head.

    Sizes are kept explicit; heads must divide dim. The MLP expansion is
    the standard 4x.

    TPU sizing note (measured, PERF.md round-4 MFU ladder): prefer
    head_dim = dim/heads = 128 — the flash kernel's QK^T and PV dots
    contract over head_dim, and 128 fills the MXU's lanes exactly
    (head_dim 64 half-fills them: h=16 -> h=8 at d=1024 alone was
    +13.5 MFU points, 44.9% -> 58.4%).
    """

    vocab: int = 64
    dim: int = 64
    heads: int = 4
    depth: int = 2
    max_seq: int = 256
    kv_heads: int = 0      # 0 = heads (MHA); < heads = grouped-query
                           # attention (1 = MQA): k/v projections and the
                           # KV cache shrink by heads/kv_heads
    pos: str = "learned"   # learned | rope (rotary, ops/attention.rope —
                           # no position table, exact under SP shards via
                           # explicit absolute positions)
    moe_experts: int = 0   # 0 = dense MLP; >0 = Switch-MoE MLP per block
                           # (parallel/ep.py), EP-shardable over a mesh axis
    moe_top_k: int = 1     # experts per token: 1 = Switch, 2 = GShard-style
    name: str = "transformer_lm"

    @property
    def head_dim(self) -> int:
        if self.dim % self.heads:
            raise ValueError(f"dim {self.dim} not divisible by heads {self.heads}")
        return self.dim // self.heads

    @property
    def n_kv(self) -> int:
        hkv = self.kv_heads or self.heads
        if hkv <= 0 or self.heads % hkv:
            # <= 0 must be caught explicitly: heads % -1 == 0 in Python,
            # and a negative count would flow into param shapes.
            raise ValueError(
                f"kv_heads must be a positive divisor of heads "
                f"{self.heads}; got {hkv}"
            )
        return hkv

    def init(self, key) -> dict:
        d, v, hd = self.dim, self.vocab, self.head_dim
        # Key budget is fixed regardless of config so the default
        # (learned-pos MHA) consumes keys exactly as in round 1 — golden
        # params stay reproducible; GQA draws one extra subkey from the
        # block key instead of shifting the stream.
        keys = iter(jax.random.split(key, 3 + 4 * self.depth))
        scale = 1.0 / math.sqrt(d)

        def dense(k, din, dout):
            return jax.random.normal(k, (din, dout), jnp.float32) / math.sqrt(din)

        params = {
            "tok_emb": jax.random.normal(next(keys), (v, d), jnp.float32) * scale,
            "ln_f": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
            "blocks": [],
        }
        pos_key = next(keys)  # drawn even for rope: keeps the stream fixed
        if self.pos == "learned":
            params["pos_emb"] = jax.random.normal(
                pos_key, (self.max_seq, d), jnp.float32
            ) * scale
        elif self.pos != "rope":
            raise ValueError(f"unknown pos {self.pos!r}; 'learned' or 'rope'")
        params["head"] = dense(next(keys), d, v)
        for _ in range(self.depth):
            blk = {
                "ln1": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
                "ln2": {"g": jnp.ones((d,)), "b": jnp.zeros((d,))},
            }
            qkv_key = next(keys)
            if self.n_kv == self.heads:
                blk["wqkv"] = dense(qkv_key, d, 3 * d)
            else:
                kq, kkv = jax.random.split(qkv_key)
                blk["wq"] = dense(kq, d, d)
                blk["wkv"] = dense(kkv, d, 2 * self.n_kv * hd)
            blk["wo"] = dense(next(keys), d, d)
            if self.moe_experts:
                from ..parallel.ep import init_moe_params

                blk["moe"] = init_moe_params(
                    next(keys), d, 4 * d, self.moe_experts
                )
                next(keys)  # keep the per-block key budget uniform
            else:
                blk["w1"] = dense(next(keys), d, 4 * d)
                blk["w2"] = dense(next(keys), 4 * d, d)
            params["blocks"].append(blk)
        return params

    def project_qkv(
        self,
        blk: dict,
        y: jnp.ndarray,                # (B, S, dim) normed activations
        *,
        positions: jnp.ndarray,        # (S,) or (B, S) absolute positions
        compute_dtype=None,
    ):
        """QKV projections + head reshape + rotary — THE one
        implementation, shared by the training forward (apply_block) and
        the cached decode core (models/generate.token_forward, which the
        contiguous decode_block AND serve/'s paged path both ride).
        Before the serve/ refactor the decode path re-implemented these
        lines and only a parity test bound the two; now they cannot
        drift. Per-row (B, S) positions are the continuous-batching
        decode form — each serving slot sits at its own depth. Weight
        matmuls route through qmatmul, so serving params may carry int8
        QuantW leaves (quantize_decode_params) — the decode-weight
        bandwidth lever, same forward.
        Returns q: (B, S, H, hd); k, v: (B, S, Hkv, hd)."""
        b, s, _ = y.shape
        h, hd, hkv = self.heads, self.head_dim, self.n_kv
        w = _weight_cast(compute_dtype)
        if hkv == h:
            qkv = qmatmul(y, w(blk["wqkv"]))        # (B, S, 3*dim)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            q = qmatmul(y, w(blk["wq"]))            # (B, S, dim)
            kv = qmatmul(y, w(blk["wkv"]))          # (B, S, 2*hkv*hd)
            k, v = jnp.split(kv, 2, axis=-1)
        q = q.reshape(b, s, h, hd)
        k = k.reshape(b, s, hkv, hd)
        v = v.reshape(b, s, hkv, hd)
        if self.pos == "rope":
            q = rope(q, positions)
            k = rope(k, positions)
        return q, k, v

    def apply_block(
        self,
        blk: dict,
        x: jnp.ndarray,                # (B, S, dim) activations
        *,
        pos: jnp.ndarray,              # (S,) absolute positions
        attn,                          # (q, k, v) -> o attention callable
        compute_dtype=None,
        moe_axis: str | None = None,
        moe_inference: bool = False,
        moe_dispatch_chunk: int = 0,
        moe_dispatch_dtype=None,
    ):
        """One pre-LN block: attention + MLP (or MoE) with residuals.

        Factored out of apply() so pipeline parallelism (parallel/pp_lm.py)
        can scan the SAME block computation over its stage's stacked
        params — one implementation of the block math for every layout.
        Returns (x, aux) with aux the MoE balance loss (0 for dense).
        """
        b, s, _ = x.shape
        h, hd = self.heads, self.head_dim
        cd = compute_dtype
        w = _weight_cast(cd)

        y = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"])
        q, k, v = self.project_qkv(blk, y, positions=pos, compute_dtype=cd)
        o = attn(q, k, v).reshape(b, s, h * hd)
        x = x + qmatmul(o.astype(x.dtype), w(blk["wo"]))
        y = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"])
        if self.moe_experts:
            # Expert weights go through the same compute-dtype cast
            # as the dense matmuls (the router's softmax stays f32
            # inside moe_mlp); without this the 16d² expert FLOPs
            # would silently promote back to f32.
            moe_p = jax.tree.map(w, blk["moe"]) if cd else blk["moe"]
            if moe_inference:
                from ..parallel.ep import moe_mlp_inference

                m = moe_mlp_inference(
                    y.reshape(b * s, self.dim), moe_p,
                    n_experts=self.moe_experts, top_k=self.moe_top_k,
                )
                aux = jnp.zeros(())
            else:
                from ..parallel.ep import moe_mlp

                m, aux = moe_mlp(
                    y.reshape(b * s, self.dim), moe_p,
                    n_experts=self.moe_experts, axis=moe_axis,
                    top_k=self.moe_top_k,
                    dispatch_chunk=moe_dispatch_chunk,
                    dispatch_dtype=moe_dispatch_dtype,
                )
            return x + m.reshape(b, s, self.dim).astype(x.dtype), aux
        return (
            x + qmatmul(jax.nn.gelu(qmatmul(y, w(blk["w1"]))),
                        w(blk["w2"])),
            jnp.zeros(()),
        )

    def apply(
        self,
        params: dict,
        tokens: jnp.ndarray,           # (B, S) int32
        *,
        attn_fn: Callable | None = None,
        pos_offset: jnp.ndarray | int = 0,
        causal: bool = True,
        remat: bool = False,           # jax.checkpoint per block
        moe_axis: str | None = None,   # mesh axis for EP expert sharding
                                       # (None = dense single-device MoE)
        moe_inference: bool = False,   # no-drop compute-all-experts MoE
                                       # (ep.moe_mlp_inference) — the
                                       # decode/prefill semantic
        moe_dispatch_chunk: int = 0,   # single-chip chunked routing
                                       # (ep.moe_mlp dispatch_chunk):
                                       # kills the quadratic dispatch
                                       # einsum term
        moe_dispatch_dtype=None,       # routing-tensor dtype override
                                       # (ep.moe_mlp dispatch_dtype);
                                       # bf16 halves the (T,E,C) build
                                       # bytes under an f32 path
        return_aux: bool = False,      # also return the MoE balance loss
        compute_dtype=None,            # e.g. jnp.bfloat16: run matmuls +
                                       # residual stream in this dtype
                                       # (master params stay f32; LN and
                                       # the caller's loss stay f32)
        return_features: bool = False,  # skip the head matmul and return
                                       # the final-LN features (B, S, dim)
                                       # — for losses that fuse the head
                                       # (train/lm.py chunked CE, which
                                       # never materializes (B,S,V) f32)
    ):                                 # (B, S, vocab) logits [, aux]
        b, s = tokens.shape
        h, hd = self.heads, self.head_dim
        cd = compute_dtype
        w = _weight_cast(cd)
        if s > self.max_seq:
            # XLA's gather would silently clamp out-of-range positions to
            # pos_emb[max_seq-1]; fail loudly instead. (Sharded callers
            # check the GLOBAL length — see make_sp_lm_train_step.)
            raise ValueError(f"sequence length {s} exceeds max_seq {self.max_seq}")
        attn = attn_fn or (lambda q, k, v: attention(q, k, v, causal=causal))
        hkv = self.n_kv

        pos = pos_offset + jnp.arange(s)
        x = params["tok_emb"][tokens]
        if self.pos == "learned":
            x = x + params["pos_emb"][pos][None, :, :]
        x = w(x)

        def block(blk, x):
            return self.apply_block(
                blk, x, pos=pos, attn=attn, compute_dtype=cd,
                moe_axis=moe_axis, moe_inference=moe_inference,
                moe_dispatch_chunk=moe_dispatch_chunk,
                moe_dispatch_dtype=moe_dispatch_dtype,
            )

        if remat:
            # Recompute block activations in the backward pass (the
            # long-context memory lever; composes with ring attention's
            # O(S/P) residency since attn_fn runs inside the checkpoint).
            block = jax.checkpoint(block)
        aux_total = jnp.zeros(())
        for blk in params["blocks"]:
            x, aux = block(blk, x)
            aux_total = aux_total + aux
        x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
        if return_features:
            return (x, aux_total) if return_aux else x
        # Head matmul in compute dtype (it is the single largest matmul);
        # logits come back in f32 — the loss softmax must not run in bf16.
        logits = qmatmul(x, w(params["head"])).astype(jnp.float32)
        return (logits, aux_total) if return_aux else logits
