"""Weight initializers.

The reference initializes every weight as `nrnd() * 0.1` where nrnd is an
Irwin-Hall(4) approximate normal: `(rnd+rnd+rnd+rnd - 2.0) * 1.724` with
rnd uniform in [0,1) (cnn.c:46-49; 1.724 ≈ sqrt(3) normalizes the variance
to ~1). Biases start at zero (calloc, cnn.c:86). All initializers here are
keyed `jax.random` — identical across processes/devices by construction,
which fixes the reference's divergent per-rank init (srand(0+rank),
cnnmpi.c:423, bug SURVEY.md 2.6c).
"""

from __future__ import annotations

import math
from collections.abc import Callable

import jax
import jax.numpy as jnp

Initializer = Callable[[jax.Array, tuple[int, ...], jnp.dtype], jnp.ndarray]


def normal(std: float = 0.1) -> Initializer:
    """Gaussian with fixed std — the reference's effective init (std 0.1)."""

    def init(key, shape, dtype=jnp.float32):
        return std * jax.random.normal(key, shape, dtype)

    return init


def irwin_hall(std: float = 0.1) -> Initializer:
    """Distribution-exact twin of the reference's nrnd (cnn.c:46-49):
    sum of four uniforms, shifted and scaled by 1.724."""

    def init(key, shape, dtype=jnp.float32):
        u = jax.random.uniform(key, (4, *shape), dtype)
        return std * ((jnp.sum(u, axis=0) - 2.0) * 1.724)

    return init


def he_normal() -> Initializer:
    """Fan-in-scaled Gaussian — what the better presets (LeNet-5/VGG on the
    ≥99% target) use instead of the reference's flat std."""

    def init(key, shape, dtype=jnp.float32):
        if len(shape) == 4:  # (kh, kw, Cin, Cout)
            fan_in = shape[0] * shape[1] * shape[2]
        else:  # (d_in, d_out)
            fan_in = shape[0]
        return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / fan_in)

    return init


_REGISTRY = {
    "normal": normal,
    "irwin_hall": irwin_hall,
    "he": lambda std=None: he_normal(),
}


def get_initializer(name: str, std: float = 0.1) -> Initializer:
    if name not in _REGISTRY:
        raise KeyError(f"unknown initializer {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](std) if name != "he" else he_normal()
