"""Autoregressive decoding for TransformerLM with a KV cache.

The training path (transformer.py) recomputes full-sequence attention;
reusing it per generated token would be O(S^2). This module adds the
standard cache: each block keeps (k, v) of static shape
(B, max_seq, H, D); a decode step writes position t with
dynamic_update_slice and attends over positions <= t via masking — all
static shapes, so the whole generate loop jits as one lax.scan program.

Prefill is NOT a separate forward implementation: it calls
`model.apply` with a k/v-capturing attn_fn, so the training forward stays
the single source of truth for the prompt pass (decode_step is the only
cached re-implementation, and the teacher-forcing parity test binds it to
apply()).

MoE blocks use `moe_mlp_inference` (compute-all-experts, top-k select) in
BOTH prefill and decode: exactly no-drop, O(T*E*H) memory, and token t's
output depends on token t alone — training's capacity-dropped dispatch
is a regularizer, not an inference semantic (it would leak other batch
rows' routing into a request's logits).

Sampling: greedy (temperature=0) or temperature-scaled categorical with a
jax.random key.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import NEG_INF, attention, rope
from .transformer import TransformerLM, _layernorm


def init_cache(model: TransformerLM, batch: int,
               dtype=jnp.float32) -> list[dict]:
    """Empty per-block KV buffers, static (B, max_seq, Hkv, head_dim) —
    under GQA the cache shrinks by heads/kv_heads (the reason serving
    stacks use GQA: cache bytes bound decode batch size). `dtype`
    bfloat16 halves the cache again: decode is cache-READ-bound (PERF.md
    decode table — tokens/s tracks cache bytes almost linearly), so the
    storage dtype is a bandwidth lever independent of GQA; scores and
    softmax stay f32 either way (_attend_cached accumulates in f32)."""
    shape = (batch, model.max_seq, model.n_kv, model.head_dim)
    return [
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(model.depth)
    ]


def prefill(model: TransformerLM, params, prompt: jnp.ndarray,
            cache_dtype=jnp.float32):
    """Batched prompt pass: ONE model.apply call whose attn_fn captures
    each block's K/V into max_seq-sized cache buffers (stored as
    `cache_dtype`; the prompt pass itself still attends at full
    precision — only the cache the DECODE steps read is quantized).

    Returns (logits_last: (B, vocab), cache).
    """
    b, s0 = prompt.shape
    if s0 > model.max_seq:
        raise ValueError(f"prompt length {s0} exceeds max_seq {model.max_seq}")
    full = (b, model.max_seq, model.n_kv, model.head_dim)
    cache: list[dict] = []

    def capture_attn(q, k, v):
        cache.append({
            "k": lax.dynamic_update_slice(
                jnp.zeros(full, cache_dtype), k.astype(cache_dtype),
                (0, 0, 0, 0),
            ),
            "v": lax.dynamic_update_slice(
                jnp.zeros(full, cache_dtype), v.astype(cache_dtype),
                (0, 0, 0, 0),
            ),
        })
        return attention(q, k, v, causal=True)

    logits = model.apply(
        params, prompt, attn_fn=capture_attn, moe_inference=True
    )
    # f32 logits regardless of the weights dtype (bf16 serving weights
    # would otherwise produce bf16 logits here and f32 in decode_step —
    # the generate scan carries logits, so the two must agree).
    return logits[:, -1, :].astype(jnp.float32), cache


def _attend_cached(q, ck, cv, pos):
    """q: (B, 1, H, D) at position `pos`; ck/cv: (B, max_seq, Hkv, D)
    with positions > pos unwritten (Hkv <= H: GQA). Masked softmax over
    the valid prefix."""
    b, one, h, d = q.shape
    hkv = ck.shape[2]
    g = h // hkv
    qg = q.reshape(b, one, hkv, g, d)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, ck, preferred_element_type=jnp.float32
    ) * scale                                       # (B, Hkv, g, 1, max_seq)
    valid = jnp.arange(ck.shape[1]) <= pos          # (max_seq,)
    logits = jnp.where(valid[None, None, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd", probs.astype(cv.dtype), cv,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, one, h, d).astype(q.dtype)


def decode_step(model: TransformerLM, params, tok, pos, cache):
    """One token through the model using/updating the cache.

    tok: (B,) int32 current tokens; pos: their position — a traced scalar
    inside generate()'s scan (bounds are enforced there; a concrete
    out-of-range pos raises here, a traced one cannot be checked).
    Returns (logits: (B, vocab), new_cache).
    """
    if isinstance(pos, int) and pos >= model.max_seq:
        raise ValueError(f"position {pos} out of range (max_seq {model.max_seq})")
    b = tok.shape[0]
    h, hd, hkv = model.heads, model.head_dim, model.n_kv
    x = params["tok_emb"][tok]                            # (B, dim)
    if model.pos == "learned":
        x = x + params["pos_emb"][pos]
    x = x[:, None, :]                                     # (B, 1, dim)
    new_cache = []
    for blk, c in zip(params["blocks"], cache):
        y = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"])
        if hkv == h:
            qkv = y @ blk["wqkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            q = y @ blk["wq"]
            k, v = jnp.split(y @ blk["wkv"], 2, axis=-1)
        q = q.reshape(b, 1, h, hd)
        k = k.reshape(b, 1, hkv, hd)
        v = v.reshape(b, 1, hkv, hd)
        if model.pos == "rope":
            # One-position rotation: positions arg is the (1,)-vector
            # [pos] (traced scalars broadcast fine).
            p1 = jnp.reshape(pos, (1,))
            q = rope(q, p1)
            k = rope(k, p1)
        ck = lax.dynamic_update_slice(c["k"], k.astype(c["k"].dtype), (0, pos, 0, 0))
        cv = lax.dynamic_update_slice(c["v"], v.astype(c["v"].dtype), (0, pos, 0, 0))
        new_cache.append({"k": ck, "v": cv})
        o = _attend_cached(q, ck, cv, pos).reshape(b, 1, h * hd)
        x = x + o @ blk["wo"]
        y = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"])
        if model.moe_experts:
            from ..parallel.ep import moe_mlp_inference

            m = moe_mlp_inference(
                y.reshape(b, model.dim), blk["moe"],
                n_experts=model.moe_experts, top_k=model.moe_top_k,
            )
            x = x + m.reshape(b, 1, model.dim)
        else:
            x = x + jax.nn.gelu(y @ blk["w1"]) @ blk["w2"]
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    return (x @ params["head"])[:, 0, :].astype(jnp.float32), new_cache


@functools.lru_cache(maxsize=64)
def _compiled_run(model: TransformerLM, s0: int, num_tokens: int,
                  temperature: float, cache_dtype: str):
    """One jitted prefill+scan program per (model, shape, sampling,
    cache dtype) combination — repeat generate() calls hit this cache
    instead of retracing."""
    cdt = jnp.dtype(cache_dtype)

    def sample(logits, k):
        if temperature <= 0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            k, logits.astype(jnp.float32) / temperature, axis=-1
        ).astype(jnp.int32)

    def gen_body(params):
        def body(carry, i):
            cache, logits, klocal = carry
            klocal, kstep = jax.random.split(klocal)
            tok = sample(logits, kstep)
            logits, cache = decode_step(model, params, tok, s0 + i, cache)
            return (cache, logits, klocal), tok

        return body

    @jax.jit
    def run(params, prompt, key):
        logits, cache = prefill(model, params, prompt, cache_dtype=cdt)
        # Scan N-1 steps (each samples from the carried logits, then runs
        # the forward that produces the NEXT logits); the final token only
        # needs a sample, not another forward.
        (_, logits, key), toks = lax.scan(
            gen_body(params), (cache, logits, key),
            jnp.arange(num_tokens - 1),
        )
        key, klast = jax.random.split(key)
        last = sample(logits, klast)
        return jnp.concatenate([toks, last[None, :]], axis=0).T

    return run


def generate(
    model: TransformerLM,
    params,
    prompt: jnp.ndarray,          # (B, S0) int32
    num_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    cache_dtype="float32",
):
    """Prefill the prompt (one batched forward), then sample `num_tokens`
    continuations with the KV-cached decode scan.

    Returns (B, num_tokens) int32. Greedy argmax at temperature 0,
    categorical sampling otherwise (key required). Prompt length +
    num_tokens must fit max_seq. `cache_dtype` "bfloat16" halves the KV
    cache bytes decode reads per token (attention scores/softmax stay
    f32); f32 is the exactness default the parity tests pin.
    """
    b, s0 = prompt.shape
    if num_tokens < 1:
        raise ValueError("num_tokens must be >= 1")
    if s0 + num_tokens > model.max_seq:
        raise ValueError(
            f"prompt {s0} + {num_tokens} new tokens exceeds max_seq "
            f"{model.max_seq}"
        )
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if key is None:
        key = jax.random.key(0)  # unused at temperature 0
    run = _compiled_run(model, s0, num_tokens, float(temperature),
                        str(jnp.dtype(cache_dtype)))
    return run(params, prompt, key)
