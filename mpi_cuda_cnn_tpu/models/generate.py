"""Autoregressive decoding for TransformerLM with a KV cache.

The training path (transformer.py) recomputes full-sequence attention;
decoding reuses it would be O(S^2) per generated token. This module adds
the standard cache: each block keeps (k, v) of static shape
(B, max_seq, H, D), a decode step writes position t with
dynamic_update_slice and attends over positions <= t via masking — all
static shapes, so the whole generate loop jits as one lax.scan program.

Works with dense and MoE blocks (single-device routing; EP-sharded decode
is not wired). Sampling: greedy (temperature=0) or temperature-scaled
categorical with a jax.random key.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import NEG_INF
from .transformer import TransformerLM, _layernorm


def init_cache(model: TransformerLM, batch: int) -> list[dict]:
    """Empty per-block KV buffers, static (B, max_seq, H, head_dim)."""
    shape = (batch, model.max_seq, model.heads, model.head_dim)
    return [
        {"k": jnp.zeros(shape, jnp.float32), "v": jnp.zeros(shape, jnp.float32)}
        for _ in range(model.depth)
    ]


def _attend_cached(q, ck, cv, pos):
    """q: (B, 1, H, D) at position `pos`; ck/cv: (B, max_seq, H, D) with
    positions > pos unwritten. Masked softmax over the valid prefix."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, ck, preferred_element_type=jnp.float32
    ) * scale                                       # (B, H, 1, max_seq)
    valid = jnp.arange(ck.shape[1]) <= pos          # (max_seq,)
    logits = jnp.where(valid[None, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(cv.dtype), cv,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


def prefill(model: TransformerLM, params, prompt: jnp.ndarray):
    """Batched prompt pass: one full-sequence forward (large causal-
    attention matmuls, not S0 sequential decode steps) that also captures
    each block's K/V into max_seq-sized cache buffers.

    Returns (logits_last: (B, vocab), cache). MoE blocks route with
    no-drop capacity, matching decode_step (see the note there).
    """
    from ..ops.attention import attention

    b, s0 = prompt.shape
    h, hd = model.heads, model.head_dim
    pos = jnp.arange(s0)
    x = params["tok_emb"][prompt] + params["pos_emb"][pos][None, :, :]
    cache = []
    full = (b, model.max_seq, h, hd)
    for blk in params["blocks"]:
        y = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"])
        qkv = y @ blk["wqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s0, h, hd)
        k = k.reshape(b, s0, h, hd)
        v = v.reshape(b, s0, h, hd)
        cache.append({
            "k": lax.dynamic_update_slice(
                jnp.zeros(full, jnp.float32), k.astype(jnp.float32), (0, 0, 0, 0)
            ),
            "v": lax.dynamic_update_slice(
                jnp.zeros(full, jnp.float32), v.astype(jnp.float32), (0, 0, 0, 0)
            ),
        })
        o = attention(q, k, v, causal=True).reshape(b, s0, h * hd)
        x = x + o @ blk["wo"]
        y = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"])
        if model.moe_experts:
            from ..parallel.ep import moe_mlp

            m, _ = moe_mlp(
                y.reshape(b * s0, model.dim), blk["moe"],
                n_experts=model.moe_experts, axis=None,
                capacity_factor=float(model.moe_experts),
            )
            x = x + m.reshape(b, s0, model.dim)
        else:
            x = x + jax.nn.gelu(y @ blk["w1"]) @ blk["w2"]
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    return (x @ params["head"])[:, -1, :], cache


def decode_step(model: TransformerLM, params, tok, pos, cache):
    """One token through the model using/updating the cache.

    tok: (B,) int32 current tokens; pos: scalar int32 their position.
    Returns (logits: (B, vocab), new_cache).
    """
    b = tok.shape[0]
    h, hd = model.heads, model.head_dim
    x = params["tok_emb"][tok] + params["pos_emb"][pos]   # (B, dim)
    x = x[:, None, :]                                     # (B, 1, dim)
    new_cache = []
    for blk, c in zip(params["blocks"], cache):
        y = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"])
        qkv = y @ blk["wqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, 1, h, hd)
        k = k.reshape(b, 1, h, hd)
        v = v.reshape(b, 1, h, hd)
        ck = lax.dynamic_update_slice(c["k"], k.astype(c["k"].dtype), (0, pos, 0, 0))
        cv = lax.dynamic_update_slice(c["v"], v.astype(c["v"].dtype), (0, pos, 0, 0))
        new_cache.append({"k": ck, "v": cv})
        o = _attend_cached(q, ck, cv, pos).reshape(b, 1, h * hd)
        x = x + o @ blk["wo"]
        y = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"])
        if model.moe_experts:
            from ..parallel.ep import moe_mlp

            # capacity_factor = E makes capacity = batch: no decode token
            # is ever dropped, so one request's output cannot depend on
            # which experts OTHER batch rows happened to pick (training's
            # capacity dropping is a regularizer; at inference it would be
            # cross-request contamination).
            m, _ = moe_mlp(
                y.reshape(b, model.dim), blk["moe"],
                n_experts=model.moe_experts, axis=None,
                capacity_factor=float(model.moe_experts),
            )
            x = x + m.reshape(b, 1, model.dim)
        else:
            x = x + jax.nn.gelu(y @ blk["w1"]) @ blk["w2"]
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    return (x @ params["head"])[:, 0, :], new_cache


@functools.lru_cache(maxsize=64)
def _compiled_run(model: TransformerLM, s0: int, num_tokens: int,
                  temperature: float):
    """One jitted prefill+scan program per (model, shape, sampling)
    combination — repeat generate() calls hit this cache instead of
    retracing."""

    def sample(logits, k):
        if temperature <= 0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            k, logits.astype(jnp.float32) / temperature, axis=-1
        ).astype(jnp.int32)

    def gen_body(params):
        def body(carry, i):
            cache, logits, klocal = carry
            klocal, kstep = jax.random.split(klocal)
            tok = sample(logits, kstep)
            logits, cache = decode_step(model, params, tok, s0 + i, cache)
            return (cache, logits, klocal), tok

        return body

    @jax.jit
    def run(params, prompt, key):
        logits, cache = prefill(model, params, prompt)
        (_, _, _), toks = lax.scan(
            gen_body(params), (cache, logits, key), jnp.arange(num_tokens)
        )
        return toks.T                                   # (B, num_tokens)

    return run


def generate(
    model: TransformerLM,
    params,
    prompt: jnp.ndarray,          # (B, S0) int32
    num_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
):
    """Prefill the prompt (one batched forward), then sample `num_tokens`
    continuations with the KV-cached decode scan.

    Returns (B, num_tokens) int32. Greedy argmax at temperature 0,
    categorical sampling otherwise (key required). Prompt length +
    num_tokens must fit max_seq.
    """
    b, s0 = prompt.shape
    if s0 + num_tokens > model.max_seq:
        raise ValueError(
            f"prompt {s0} + {num_tokens} new tokens exceeds max_seq "
            f"{model.max_seq}"
        )
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if key is None:
        key = jax.random.key(0)  # unused at temperature 0
    run = _compiled_run(model, s0, num_tokens, float(temperature))
    return run(params, prompt, key)
