"""Autoregressive decoding for TransformerLM with a KV cache.

The training path (transformer.py) recomputes full-sequence attention;
reusing it per generated token would be O(S^2). This module adds the
standard cache: each block keeps (k, v) of static shape
(B, max_seq, H, D); a decode step writes position t with
dynamic_update_slice and attends over positions <= t via masking — all
static shapes, so the whole generate loop jits as one lax.scan program.

Prefill is NOT a separate forward implementation: it calls
`model.apply` with a k/v-capturing attn_fn, so the training forward stays
the single source of truth for the prompt pass (decode_step is the only
cached re-implementation, and the teacher-forcing parity test binds it to
apply()).

MoE blocks use `moe_mlp_inference` (compute-all-experts, top-k select) in
BOTH prefill and decode: exactly no-drop, O(T*E*H) memory, and token t's
output depends on token t alone — training's capacity-dropped dispatch
is a regularizer, not an inference semantic (it would leak other batch
rows' routing into a request's logits).

Sampling: greedy (temperature=0) or temperature-scaled categorical with a
jax.random key.

Two cache LAYOUTS share one decode implementation: token_forward is the
skeleton (embedding/QKV/MoE/head — QKV via transformer.project_qkv, the
same code the training forward runs) and attend_kv the masked attention
read; the contiguous max_seq buffers here and serve/paged_cache.py's
page-pool layout differ only in how cache rows are materialized.
decode_step/decode_block accept either (pass a serve.PagedKVCache with
per-slot positions for the continuous-batching form).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import NEG_INF, attention
from ..ops.pallas_gemv import qmatmul
from .transformer import TransformerLM, _layernorm

# THE auto-dtype routing table (ISSUE 12 satellite: one place for every
# "auto" storage-dtype decision), keyed by surface -> (GQA/MQA pick,
# MHA pick). Cache row: measurement-driven (PERF.md int8 decode table,
# one v5e) — int8 wins +27-32% under GQA/MQA and LOSES MHA by ~9%,
# where bfloat16 wins outright. Weights row: under GQA/MQA the weight
# stream is the dominant byte mover once the cache is int8-shrunk, so
# int8 follows the same byte-dominance argument (chip rows banked by
# tpu_capture's bench_decode --weights-dtype steps); at MHA the cache
# dominates and the measured bf16-weights cast was NOT a win
# (PERF.md round-5 note), so weights stay f32 there.
_AUTO_DTYPE_ROUTING: dict[str, tuple[str, str]] = {
    "cache": ("int8", "bfloat16"),
    "weights": ("int8", "float32"),
}


def _route_auto(surface: str, dtype: str, heads: int,
                kv_heads: int | None) -> str:
    if dtype != "auto":
        return dtype
    gqa_pick, mha_pick = _AUTO_DTYPE_ROUTING[surface]
    kv = kv_heads or heads
    return gqa_pick if kv < heads else mha_pick


def pick_cache_dtype(dtype: str, *, heads: int,
                     kv_heads: int | None = None) -> str:
    """Resolve --decode-cache-dtype "auto" to a concrete storage dtype
    (VERDICT item 7), the pick_attn_impl pattern applied to the cache:
    int8 for GQA/MQA, bfloat16 for MHA (_AUTO_DTYPE_ROUTING "cache"
    row). Explicit dtypes pass through untouched — "auto" is a router,
    not a cap, exactly like pick_attn_impl's contract."""
    return _route_auto("cache", dtype, heads, kv_heads)


def pick_weights_dtype(dtype: str, *, heads: int,
                       kv_heads: int | None = None) -> str:
    """Resolve --decode-weights-dtype "auto" (ISSUE 12): int8 for
    GQA/MQA — where the weight stream dominates the decode bytes once
    the cache is int8 — float32 for MHA, where the cache dominates and
    the measured bf16 weights cast was not a win (_AUTO_DTYPE_ROUTING
    "weights" row; same pass-through contract as pick_cache_dtype)."""
    return _route_auto("weights", dtype, heads, kv_heads)


def init_cache(model: TransformerLM, batch: int,
               dtype=jnp.float32) -> list[dict]:
    """Empty per-block KV buffers, static (B, max_seq, Hkv, head_dim) —
    under GQA the cache shrinks by heads/kv_heads (the reason serving
    stacks use GQA: cache bytes bound decode batch size). `dtype`
    bfloat16 halves the cache again: decode is cache-READ-bound (PERF.md
    decode table — tokens/s tracks cache bytes almost linearly), so the
    storage dtype is a bandwidth lever independent of GQA; scores and
    softmax stay f32 either way (decode_block accumulates in f32).

    `dtype` int8 is the next factor-2: k/v quantize per (position, head)
    — absmax/127 scales stored alongside as f32 (B, S, Hkv, 1): +4
    bytes per 512-byte f32 row at head_dim 128 (0.8% of the f32 cache's
    bytes; ~3% of the int8 cache's). The scales never enter the MXU
    contractions: a k-row's scale is constant along the contracted
    head_dim, so it multiplies the LOGITS after the QK dot, and a
    v-row's scale folds into the probabilities before the PV dot. The
    STORED cache is pure int8 (the bandwidth lever); decode_block's
    einsums consume it through an int8->f32 convert, whose cost shows
    at the MHA shape (PERF.md round-5 decode table: int8 wins +27-32%
    at GQA/MQA, loses ~9% at MHA where the convert spans 8x the
    bytes)."""
    shape = (batch, model.max_seq, model.n_kv, model.head_dim)
    if jnp.dtype(dtype) == jnp.int8:
        sshape = shape[:-1] + (1,)
        return [
            {"k": jnp.zeros(shape, jnp.int8),
             "ks": jnp.zeros(sshape, jnp.float32),
             "v": jnp.zeros(shape, jnp.int8),
             "vs": jnp.zeros(sshape, jnp.float32)}
            for _ in range(model.depth)
        ]
    return [
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(model.depth)
    ]


def _quant_kv(x):
    """Per-(batch, position, head) absmax int8 quantization of a
    (B, T, Hkv, head_dim) k/v tensor: returns (int8 values, f32 scales
    (B, T, Hkv, 1)) with x ≈ values * scales."""
    xf = x.astype(jnp.float32)
    s = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-10)
    q = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    return q, s


def prefill(model: TransformerLM, params, prompt: jnp.ndarray,
            cache_dtype=jnp.float32):
    """Batched prompt pass: ONE model.apply call whose attn_fn captures
    each block's K/V into max_seq-sized cache buffers (stored as
    `cache_dtype`; the prompt pass itself still attends at full
    precision — only the cache the DECODE steps read is quantized).

    Returns (logits_last: (B, vocab), cache).
    """
    b, s0 = prompt.shape
    if s0 > model.max_seq:
        raise ValueError(f"prompt length {s0} exceeds max_seq {model.max_seq}")
    full = (b, model.max_seq, model.n_kv, model.head_dim)
    sfull = full[:-1] + (1,)
    int8 = jnp.dtype(cache_dtype) == jnp.int8
    cache: list[dict] = []

    def capture_attn(q, k, v):
        if int8:
            qk, sk = _quant_kv(k)
            qv, sv = _quant_kv(v)
            cache.append({
                "k": lax.dynamic_update_slice(
                    jnp.zeros(full, jnp.int8), qk, (0, 0, 0, 0)
                ),
                "ks": lax.dynamic_update_slice(
                    jnp.zeros(sfull, jnp.float32), sk, (0, 0, 0, 0)
                ),
                "v": lax.dynamic_update_slice(
                    jnp.zeros(full, jnp.int8), qv, (0, 0, 0, 0)
                ),
                "vs": lax.dynamic_update_slice(
                    jnp.zeros(sfull, jnp.float32), sv, (0, 0, 0, 0)
                ),
            })
        else:
            cache.append({
                "k": lax.dynamic_update_slice(
                    jnp.zeros(full, cache_dtype), k.astype(cache_dtype),
                    (0, 0, 0, 0),
                ),
                "v": lax.dynamic_update_slice(
                    jnp.zeros(full, cache_dtype), v.astype(cache_dtype),
                    (0, 0, 0, 0),
                ),
            })
        return attention(q, k, v, causal=True)

    logits = model.apply(
        params, prompt, attn_fn=capture_attn, moe_inference=True
    )
    # f32 logits regardless of the weights dtype (bf16 serving weights
    # would otherwise produce bf16 logits here and f32 in decode_step —
    # the generate scan carries logits, so the two must agree).
    return logits[:, -1, :].astype(jnp.float32), cache


def decode_step(model: TransformerLM, params, tok, pos, cache):
    """One token through the model using/updating the cache — the k=1
    case of decode_block (one forward implementation; the speculative
    path's greedy-exactness depends on the two never drifting).

    tok: (B,) int32 current tokens; pos: their position — a traced scalar
    inside generate()'s scan (bounds are enforced there; a concrete
    out-of-range pos raises here, a traced one cannot be checked).
    Returns (logits: (B, vocab), new_cache).
    """
    logits, new_cache = decode_block(model, params, tok[:, None], pos, cache)
    return logits[:, 0, :], new_cache


def token_forward(model: TransformerLM, params, toks, positions, attend):
    """THE cached-decode forward skeleton: k tokens per row at explicit
    absolute positions, with the attention/cache behavior injected per
    layer. Everything around attention — embedding, layernorms, QKV
    projections + rotary (transformer.project_qkv, shared with the
    training forward), MoE/dense MLP, final head — has exactly one
    implementation; the contiguous decode_block and serve/'s paged
    continuous-batching path differ ONLY in their `attend`.

    toks: (B, k) int32; positions: (k,) shared across rows, or (B, k)
    PER-ROW absolute positions (the serving form — each slot sits at
    its own depth). attend(i, q, k, v) -> (B, k, H*hd) f32 performs
    layer i's cache update + masked attention read (closing over its
    cache; layers are traced in order, so append-style capture works —
    the same idiom as prefill's attn_fn).

    Every weight matmul routes through ops.pallas_gemv.qmatmul, so
    params may carry int8 QuantW leaves (quantize_decode_params,
    --decode-weights-dtype int8) — the decode-weight bandwidth lever
    rides the SAME forward, not a second one.
    Returns (B, k, vocab) f32 logits.
    """
    b, kk = toks.shape
    x = params["tok_emb"][toks]                           # (B, k, dim)
    if model.pos == "learned":
        # (k, dim) broadcasts over rows; (B, k, dim) indexes per row.
        x = x + params["pos_emb"][positions]
    for i, blk in enumerate(params["blocks"]):
        y = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"])
        q, k, v = model.project_qkv(blk, y, positions=positions)
        o = attend(i, q, k, v)
        x = x + qmatmul(o.astype(x.dtype), blk["wo"])
        y = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"])
        if model.moe_experts:
            from ..parallel.ep import moe_mlp_inference

            m = moe_mlp_inference(
                y.reshape(b * kk, model.dim), blk["moe"],
                n_experts=model.moe_experts, top_k=model.moe_top_k,
            )
            x = x + m.reshape(b, kk, model.dim)
        else:
            x = x + qmatmul(jax.nn.gelu(qmatmul(y, blk["w1"])),
                            blk["w2"])
    x = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
    return qmatmul(x, params["head"]).astype(jnp.float32)


def attend_kv(q, ck, cv, mask, cks=None, cvs=None):
    """THE masked GQA attention read over materialized cache rows — the
    one implementation both cache layouts consume (the contiguous
    max_seq buffers here, the paged gather in serve/paged_cache.py; the
    paged-vs-contiguous bitwise parity rests on this being shared).

    q: (B, k, H, hd); ck/cv: (B, L, Hkv, hd) cache rows (any storage
    dtype; int8 rows come with cks/cvs absmax scales (B, L, Hkv, 1),
    applied OUTSIDE the dots — a key row's scale is constant along the
    contracted head_dim so it factors onto the logits, a value row's
    folds into the probabilities before the PV contraction). mask:
    (k, L) or (B, k, L) bool, True = attend; scores/softmax are f32.
    Returns (B, k, H*hd) f32.
    """
    b, kk, h, hd = q.shape
    hkv = ck.shape[2]
    int8 = ck.dtype == jnp.int8
    g = h // hkv
    qg = q.reshape(b, kk, hkv, g, hd)
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    # The single-query gemv cell (g*kk == 1: MHA one-token decode) uses
    # sum-product contractions instead of einsums when accumulating in
    # f32 OFF-TPU: XLA CPU's batched-gemv emitter orders its
    # accumulation differently from any per-(b,h) dot a fused kernel
    # can express, so einsums there are unreproducible to the bit. The
    # sum-product is the one formulation XLA CPU emits identically
    # inside and outside a Pallas kernel — ops/pallas_paged_attention
    # mirrors it (same backend switch), which is what makes the paged
    # kernel's f32 parity gate BITWISE across MHA too, exactly where it
    # is tested (interpret mode on CPU). On TPU both sides keep the
    # batched einsum/dot — the MXU path the banked MHA decode rows
    # measure; the kernel-vs-gather contract there is the bf16/int8
    # band, not bitwise f32 (nothing serving-shaped runs f32 MHA on
    # chip, and the CPU gate pins the kernel's indexing either way).
    # bf16 keeps the einsums everywhere (the kernel's bf16 dots already
    # land bitwise inside bf16 rounding).
    sumprod = (kk * g == 1 and (int8 or ck.dtype == jnp.float32)
               and jax.default_backend() != "tpu")
    if sumprod:
        qv = qg[:, 0, :, 0, :]                # (B, Hkv, hd)
        ckf = ck.astype(jnp.float32) if int8 else ck
        logits = (jnp.sum(
            qv[:, :, :, None] * jnp.transpose(ckf, (0, 2, 3, 1)), axis=2,
        ) * scale)[:, :, None, None, :]       # (B, Hkv, 1, 1, L)
    else:
        logits = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qg,
            ck.astype(jnp.float32) if int8 else ck,
            preferred_element_type=jnp.float32,
        ) * scale                             # (B, Hkv, g, k, L)
    if int8:
        logits = logits * jnp.transpose(cks, (0, 2, 3, 1))[:, :, None, :, :]
    if mask.ndim == 2:
        mask = mask[None]                     # shared across rows
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if int8:
        if sumprod:
            pq = probs[:, :, 0, 0, :] * cvs[:, :, :, 0].transpose(0, 2, 1)
            o = jnp.sum(
                pq[:, :, :, None]
                * jnp.transpose(cv.astype(jnp.float32), (0, 2, 1, 3)),
                axis=2,
            )[:, None]                          # (B, 1, Hkv, hd)
        else:
            pv = probs * jnp.transpose(cvs, (0, 2, 3, 1))[:, :, None, :, :]
            o = jnp.einsum(
                "bhgqk,bkhd->bqhgd", pv, cv.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
    elif sumprod:
        o = jnp.sum(
            probs[:, :, 0, 0, :, None] * jnp.transpose(cv, (0, 2, 1, 3)),
            axis=2,
        )[:, None]                              # (B, 1, Hkv, hd)
    else:
        o = jnp.einsum(
            "bhgqk,bkhd->bqhgd", probs.astype(cv.dtype), cv,
            preferred_element_type=jnp.float32,
        )
    return o.reshape(b, kk, h * hd)


def attend_contiguous(c, q, k, v, pos, positions):
    """Contiguous-cache attend: write k/v at [pos, pos+k) of the static
    (B, max_seq, Hkv, hd) buffers, then attend each row i over keys at
    positions <= positions[i] (attend_kv does the masked read).
    Returns (o: (B, k, H*hd) f32, new_c)."""
    int8 = c["k"].dtype == jnp.int8
    if int8:
        qk8, sk8 = _quant_kv(k)
        qv8, sv8 = _quant_kv(v)
        new_c = {
            "k": lax.dynamic_update_slice(c["k"], qk8, (0, pos, 0, 0)),
            "ks": lax.dynamic_update_slice(c["ks"], sk8, (0, pos, 0, 0)),
            "v": lax.dynamic_update_slice(c["v"], qv8, (0, pos, 0, 0)),
            "vs": lax.dynamic_update_slice(c["vs"], sv8, (0, pos, 0, 0)),
        }
    else:
        new_c = {
            "k": lax.dynamic_update_slice(c["k"], k.astype(c["k"].dtype),
                                          (0, pos, 0, 0)),
            "v": lax.dynamic_update_slice(c["v"], v.astype(c["v"].dtype),
                                          (0, pos, 0, 0)),
        }
    # Rows attend over the cached prefix + the block's causal part:
    # row i sees keys at positions <= pos+i.
    mask = (jnp.arange(new_c["k"].shape[1])[None, :]
            <= positions[:, None])            # (k, max_seq)
    o = attend_kv(q, new_c["k"], new_c["v"], mask,
                  cks=new_c.get("ks"), cvs=new_c.get("vs"))
    return o, new_c


def decode_block(model: TransformerLM, params, toks, pos, cache):
    """k tokens through the model at positions [pos, pos+k): the block
    form of decode_step, for speculative verification — ONE forward
    scores k candidate tokens instead of k sequential decode steps.

    toks: (B, k) int32; pos: start position (traced scalar OK; a
    concrete out-of-range block raises here — dynamic_update_slice
    would otherwise clamp the write start while positions/RoPE/mask use
    the unclamped pos, silently corrupting the cache). Writes all k
    cache slots FIRST, then attends each row i over keys <= pos+i — so
    within-block causality holds and any stale entries beyond the
    accepted prefix from a previous speculative round are either
    overwritten here or masked by the row bound.

    `cache` may also be a serve.paged_cache.PagedKVCache (pos then may
    be a (B,) per-slot vector) — the decode surface accepts either
    cache layout. Detection is by the block_table attribute, so the
    serve package only loads when a paged cache is actually passed
    (models/ must not depend on serve/ — serve/ imports THIS module).
    Returns (logits: (B, k, vocab), new_cache).
    """
    if hasattr(cache, "block_table"):
        from ..serve.paged_cache import paged_decode_block

        return paged_decode_block(model, params, toks, pos, cache)
    b, kk = toks.shape
    if isinstance(pos, int) and pos + kk > model.max_seq:
        raise ValueError(
            f"block [{pos}, {pos + kk}) out of range (max_seq "
            f"{model.max_seq})"
        )
    positions = pos + jnp.arange(kk)
    new_cache = []

    def attend(i, q, k, v):
        o, new_c = attend_contiguous(cache[i], q, k, v, pos, positions)
        new_cache.append(new_c)
        return o

    logits = token_forward(model, params, toks, positions, attend)
    return logits, new_cache


def filter_logits(logits, top_k: int = 0, top_p: float = 0.0):
    """Top-k / nucleus (top-p) restriction: logits outside the kept set
    go to NEG_INF. top_k keeps the k largest (ties at the boundary all
    survive — the standard threshold form; values above the vocab size
    clamp to it — keeping everything — instead of indexing out of
    range); top_p keeps the smallest prefix of the probability-sorted
    vocabulary whose mass reaches p. Both may combine; 0 disables
    either. Pure and shape-preserving, so it composes with
    jax.random.categorical and jits inside the decode scan."""
    l = logits.astype(jnp.float32)
    if top_k:
        thr = jnp.sort(l, axis=-1)[..., -min(top_k, l.shape[-1]), None]
        l = jnp.where(l >= thr, l, NEG_INF)
    if top_p:
        sorted_l = jnp.sort(l, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        # Mass BEFORE each token: tokens whose preceding cumulative mass
        # already reaches p are cut; the boundary token stays (the set
        # must reach p, not stop short of it).
        cum_before = jnp.cumsum(probs, axis=-1) - probs
        kept = cum_before < top_p
        cutoff = jnp.min(
            jnp.where(kept, sorted_l, jnp.inf), axis=-1, keepdims=True
        )
        l = jnp.where(l >= cutoff, l, NEG_INF)
    return l


@functools.lru_cache(maxsize=64)
def _compiled_run(model: TransformerLM, s0: int, num_tokens: int,
                  temperature: float, cache_dtype: str,
                  top_k: int, top_p: float):
    """One jitted prefill+scan program per (model, shape, sampling,
    cache dtype) combination — repeat generate() calls hit this cache
    instead of retracing."""
    cdt = jnp.dtype(cache_dtype)

    def sample(logits, k):
        if temperature <= 0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # Temperature FIRST, then the nucleus: the kept set must be
        # computed on the distribution actually sampled (top_p on the
        # flattened T>1 distribution keeps more tokens — the standard
        # semantics; top_k is temperature-invariant either way).
        l = filter_logits(logits.astype(jnp.float32) / temperature,
                          top_k, top_p)
        return jax.random.categorical(k, l, axis=-1).astype(jnp.int32)

    def gen_body(params):
        def body(carry, i):
            cache, logits, klocal = carry
            klocal, kstep = jax.random.split(klocal)
            tok = sample(logits, kstep)
            logits, cache = decode_step(model, params, tok, s0 + i, cache)
            return (cache, logits, klocal), tok

        return body

    @jax.jit
    def run(params, prompt, key):
        logits, cache = prefill(model, params, prompt, cache_dtype=cdt)
        # Scan N-1 steps (each samples from the carried logits, then runs
        # the forward that produces the NEXT logits); the final token only
        # needs a sample, not another forward.
        (_, logits, key), toks = lax.scan(
            gen_body(params), (cache, logits, key),
            jnp.arange(num_tokens - 1),
        )
        key, klast = jax.random.split(key)
        last = sample(logits, klast)
        return jnp.concatenate([toks, last[None, :]], axis=0).T

    return run


def _emit_rows(y, accept, out, n_out):
    """Buffered emit shared by the greedy and sampling acceptance paths:
    y (1, k) emit rows, accept (k-1,) bool prefix flags. j = 1 + the
    accepted prefix length (row j-1 is the first-reject replacement or
    the bonus row); ALL k rows are written at n_out — rows beyond j are
    rewritten by the next round's write. Returns (j, new cur, out)."""
    j = 1 + jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))
    out = lax.dynamic_update_slice(out, y, (0, n_out))
    cur = lax.dynamic_slice(y, (0, j - 1), (1, 1))[:, 0]
    return j, cur, out


def _accept_and_emit(u, y, out, n_out):
    """The GREEDY speculative acceptance core, shared by the model-draft
    and prompt-lookup runners so the two can never drift: u (1, k)
    verify inputs, y (1, k) target argmax picks. Accept the longest
    prefix where input i+1 equals the target's pick at row i (j in
    [1, k] tokens emitted per round). The serving engine's batched
    form (ISSUE 14) applies the SAME law host-side per slot —
    serve/spec.accept_len — and a randomized equivalence test
    (tests/test_spec_serve.py) pins the two dialects against drift."""
    matches = u[0, 1:] == y[0, :-1]
    return _emit_rows(y, matches, out, n_out)


def _filtered_probs(logits, temperature, top_k, top_p):
    """f32 probabilities of temperature-scaled, top-k/top-p-restricted
    logits — the distribution `generate()` actually samples from; the
    speculative sampling paths must target exactly this law."""
    l = filter_logits(logits.astype(jnp.float32) / temperature, top_k, top_p)
    return jax.nn.softmax(l, axis=-1)


def _spec_sample_rows(tl, qs, u, key, temperature, top_k, top_p):
    """Rejection-sampling acceptance for one verify block (B=1) — the
    T>0 analog of _accept_and_emit's matching, implementing the standard
    speculative sampling theorem (accept draft token x w.p.
    min(1, p(x)/q(x)); replace a reject with a sample from the residual
    norm(max(p-q, 0)); after a fully accepted chain, sample the bonus
    row from p directly). The emitted token at every row is then
    distributed EXACTLY as p for ANY proposal law q — the draft moves
    the speed, never the law (tests/test_spec_sampling.py pins this
    against analytic distributions).

    tl: (1, k, V) target logits — row i is the target's distribution
        for the token following verify input u[:, i];
    qs: (k-1, V) f32 draft probabilities — row i is the law proposal
        u[:, i+1] was drawn from (a one-hot delta for prompt-lookup);
    u:  (1, k) int32 verify inputs (u[:, 0] is already emitted).
    Returns (y: (1, k) int32 emit rows, accept: (k-1,) bool).
    """
    kk = tl.shape[1]
    p = _filtered_probs(tl[0], temperature, top_k, top_p)      # (k, V)
    props = u[0, 1:]                                           # (k-1,)
    ku, kr, kb = jax.random.split(key, 3)
    p_prop = jnp.take_along_axis(p[:-1], props[:, None], axis=-1)[:, 0]
    q_prop = jnp.take_along_axis(qs, props[:, None], axis=-1)[:, 0]
    # u*q < p  <=>  u < min(1, p/q) (u < 1 surely); q = 0 accepts iff
    # p > 0 — a proposal the target filters out (p = 0) always rejects.
    unif = jax.random.uniform(ku, (kk - 1,))
    accept = unif * q_prop < p_prop
    # Residual for each non-bonus row: norm(max(p - q, 0)). A row can be
    # identically zero two ways: p == q exactly (never selected —
    # acceptance there is 1, the sample unused) or p <= q everywhere by
    # ROUNDING while p < q at the proposal (rejection still possible,
    # and categorical over an all -inf row would deterministically emit
    # token 0, even one with p = 0). Guard the degenerate row by
    # falling back to sampling from p itself — within the same rounding
    # band that zeroed the residual, so the output law stays exact to
    # float precision (ADVICE round 5).
    res = jnp.maximum(p[:-1] - qs, 0.0)
    res = jnp.where(
        jnp.sum(res, axis=-1, keepdims=True) > 0.0, res, p[:-1]
    )
    res_tok = jax.random.categorical(kr, jnp.log(res), axis=-1)
    bonus = jax.random.categorical(kb, jnp.log(p[-1]))
    y_head = jnp.where(accept, props, res_tok.astype(jnp.int32))
    y = jnp.concatenate([y_head, bonus[None].astype(jnp.int32)])
    return y[None, :], accept


@functools.lru_cache(maxsize=16)
def _compiled_spec_run(model: TransformerLM, draft: TransformerLM,
                       s0: int, num_tokens: int, k: int, cache_dtype: str,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 0.0):
    """Jitted speculative loop for one (models, shapes, sampling) combo:
    greedy exact-match acceptance at temperature 0, rejection sampling
    (draft samples its own filtered law; _spec_sample_rows targets the
    filtered target law) at temperature > 0."""
    cdt = jnp.dtype(cache_dtype)
    sampling = temperature > 0

    @jax.jit
    def run(params, draft_params, prompt, key):
        tl, t_cache = prefill(model, params, prompt, cache_dtype=cdt)
        dl, d_cache = prefill(draft, draft_params, prompt, cache_dtype=cdt)
        del dl  # the draft's prompt logits are not used: the first
        #         generated token is the TARGET's own pick/sample
        if sampling:
            key, k0 = jax.random.split(key)
            cur = jax.random.categorical(
                k0, jnp.log(_filtered_probs(tl, temperature, top_k, top_p))
            ).astype(jnp.int32)                               # (1,)
        else:
            cur = jnp.argmax(tl, axis=-1).astype(jnp.int32)   # (1,)
        out = jnp.zeros((1, num_tokens + k), jnp.int32)
        out = lax.dynamic_update_slice(out, cur[:, None], (0, 0))

        def draft_step(carry, _):
            tok, pos, dc, kd = carry
            logits, dc = decode_step(draft, draft_params, tok, pos, dc)
            if sampling:
                q = _filtered_probs(logits, temperature, top_k, top_p)
                kd, ks = jax.random.split(kd)
                nxt = jax.random.categorical(
                    ks, jnp.log(q)
                ).astype(jnp.int32)
            else:
                q = jnp.zeros_like(logits)        # unused in greedy mode
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (nxt, pos + 1, dc, kd), (nxt, q[0])

        def round_body(state):
            pos, cur, t_cache, d_cache, out, n_out, rounds, key = state
            # 1. Draft k sequential steps, INGESTING each fed token so
            #    its cache stays aligned with the verified prefix; the
            #    last proposal is never fed anywhere (d_k is unused).
            key, kd, kv = jax.random.split(key, 3)
            (_, _, d_cache, _), (ds, qs) = lax.scan(
                draft_step, (cur, pos, d_cache, kd), None, length=k
            )                     # ds: (k, 1) proposals; qs: (k, V) laws
            u = jnp.concatenate([cur[None, :], ds[: k - 1, :]],
                                axis=0).T         # (1, k) verify inputs
            # 2. One target block forward scores all k inputs.
            tl, t_cache = decode_block(model, params, u, pos, t_cache)
            # 3./4. Acceptance + buffered emit — exact-match (greedy) or
            #    rejection-sampling (_spec_sample_rows), same emit core.
            if sampling:
                y, accept = _spec_sample_rows(
                    tl, qs[: k - 1], u, kv, temperature, top_k, top_p
                )
                j, cur, out = _emit_rows(y, accept, out, n_out)
            else:
                y = jnp.argmax(tl, axis=-1).astype(jnp.int32)  # (1, k)
                j, cur, out = _accept_and_emit(u, y, out, n_out)
            return (pos + j, cur, t_cache, d_cache, out, n_out + j,
                    rounds + 1, key)

        def cond(state):
            return state[5] < num_tokens

        state = (jnp.asarray(s0), cur, t_cache, d_cache, out,
                 jnp.asarray(1), jnp.asarray(0), key)
        pos, cur, _, _, out, n_out, rounds, _ = lax.while_loop(
            cond, round_body, state
        )
        return out[:, :num_tokens], n_out, rounds

    return run


@functools.lru_cache(maxsize=16)
def _compiled_lookup_run(model: TransformerLM, s0: int, num_tokens: int,
                         k: int, ngram: int, cache_dtype: str,
                         temperature: float = 0.0, top_k: int = 0,
                         top_p: float = 0.0):
    """Jitted prompt-lookup speculative loop (draft-free). At
    temperature > 0 the deterministic proposal is a one-hot law, so
    rejection sampling degenerates to: accept proposal x w.p. p(x),
    resample from p-with-x-zeroed on reject — still exactly p."""
    cdt = jnp.dtype(cache_dtype)
    L = model.max_seq
    V = model.vocab
    sampling = temperature > 0

    @jax.jit
    def run(params, prompt, key):
        tl, t_cache = prefill(model, params, prompt, cache_dtype=cdt)
        if sampling:
            key, k0 = jax.random.split(key)
            cur = jax.random.categorical(
                k0, jnp.log(_filtered_probs(tl, temperature, top_k, top_p))
            ).astype(jnp.int32)                               # (1,)
        else:
            cur = jnp.argmax(tl, axis=-1).astype(jnp.int32)   # (1,)
        ctx = jnp.zeros((1, L), jnp.int32)
        ctx = lax.dynamic_update_slice(ctx, prompt, (0, 0))
        ctx = lax.dynamic_update_slice(ctx, cur[:, None], (0, s0))
        out = jnp.zeros((1, num_tokens + k), jnp.int32)
        out = lax.dynamic_update_slice(out, cur[:, None], (0, 0))

        def propose(ctx, pos, cur):
            """The k-1 tokens that followed the MOST RECENT earlier
            occurrence of the context's current ngram-token tail
            (ctx[pos] == cur is already written). No match -> repeat
            cur: acceptance just collapses to 1, never an error. When
            the match sits within k-1 of the buffer end, the window
            start clamps to L-(k-1): the proposals then trail the
            clamped window (not the match) — acceptance drops, the
            contract (tokens come from ctx) holds."""
            idx = jnp.arange(L)
            match = (idx >= ngram - 1) & (idx < pos)
            row = ctx[0]
            for d in range(ngram):
                # row[j-d] vs row[pos-d]; jnp.roll wraps for j < d but
                # those rows are outside the idx >= ngram-1 window.
                match &= jnp.roll(row, d) == row[pos - d]
            j = jnp.max(jnp.where(match, idx, -1))
            start = jnp.clip(j + 1, 0, L - (k - 1))
            props = lax.dynamic_slice(ctx, (0, start), (1, k - 1))[0]
            return jnp.where(j >= 0, props,
                             jnp.broadcast_to(cur, (k - 1,)))

        def round_body(state):
            pos, cur, t_cache, ctx, out, n_out, rounds, key = state
            props = propose(ctx, pos, cur)
            u = jnp.concatenate([cur, props])[None, :]        # (1, k)
            tl, t_cache = decode_block(model, params, u, pos, t_cache)
            if sampling:
                key, kv = jax.random.split(key)
                qs = jax.nn.one_hot(props, V, dtype=jnp.float32)
                y, accept = _spec_sample_rows(
                    tl, qs, u, kv, temperature, top_k, top_p
                )
                j, cur, out = _emit_rows(y, accept, out, n_out)
            else:
                y = jnp.argmax(tl, axis=-1).astype(jnp.int32)
                j, cur, out = _accept_and_emit(u, y, out, n_out)
            # Keep the context buffer current: the accepted picks land
            # at pos+1.. (rows beyond j overwritten next round, same
            # trick as `out`; ctx[pos+j] == new cur by construction).
            ctx = lax.dynamic_update_slice(ctx, y, (0, pos + 1))
            return (pos + j, cur, t_cache, ctx, out, n_out + j,
                    rounds + 1, key)

        def cond(state):
            return state[5] < num_tokens

        state = (jnp.asarray(s0), cur, t_cache, ctx, out,
                 jnp.asarray(1), jnp.asarray(0), key)
        pos, cur, _, _, out, n_out, rounds, _ = lax.while_loop(
            cond, round_body, state
        )
        return out[:, :num_tokens], n_out, rounds

    return run


def _validate_spec_sampling(temperature, key, top_k, top_p, vocab):
    """Shared sampling-argument validation for the speculative paths —
    the same contract generate() enforces."""
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if top_k < 0 or top_k > vocab:
        raise ValueError(f"top_k {top_k} not in [0, vocab {vocab}]")
    if not 0.0 <= top_p <= 1.0:
        raise ValueError(f"top_p {top_p} not in [0, 1]")
    if (top_k or top_p) and temperature <= 0:
        raise ValueError(
            "top_k/top_p restrict SAMPLING — set temperature > 0 "
            "(greedy argmax already takes the single most likely token)"
        )


def _spec_stats(n_out, rounds, num_tokens):
    """Acceptance stats with the emitted count CAPPED at num_tokens: the
    final round may overshoot the budget by up to k-1 accepted tokens
    that never land in the returned buffer — counting them would inflate
    the rate (round-4 advisor finding)."""
    r = max(int(rounds), 1)
    return {"rounds": int(rounds),
            "mean_accepted": (min(int(n_out), num_tokens) - 1) / r}


def lookup_speculative_generate(
    model: TransformerLM,
    params,
    prompt: jnp.ndarray,          # (1, S0) int32 — latency path, B = 1
    num_tokens: int,
    *,
    k: int = 8,
    ngram: int = 2,
    cache_dtype="float32",
    temperature: float = 0.0,
    key: jax.Array | None = None,
    top_k: int = 0,
    top_p: float = 0.0,
    return_stats: bool = False,
):
    """Draft-FREE speculative decoding (prompt lookup): propose the k-1
    tokens that followed the most recent earlier occurrence of the
    current n-gram in the running context (prompt + generated), and
    verify with the same one-block-forward machinery as
    speculative_generate. No second model — this is the form the lm
    CLI's --sample-speculative-k reaches — and it shines on repetitive
    text (code, logs, structured data), where the continuation often
    already appeared verbatim. Same B=1 restriction and exactness
    contract as speculative_generate: bitwise greedy at temperature 0;
    at temperature > 0, rejection sampling against the one-hot proposal
    law (accept w.p. p(prop), resample the zeroed residual) — the
    output law is exactly plain sampling's (tests/test_spec_sampling).
    """
    b, s0 = prompt.shape
    if b != 1:
        raise ValueError(f"speculative decoding is the B=1 latency path "
                         f"(got batch {b}); use generate() for batches")
    if num_tokens < 1:
        raise ValueError("num_tokens must be >= 1")
    if k < 2:
        raise ValueError(f"k must be >= 2 (k={k} would propose nothing)")
    if ngram < 1:
        raise ValueError(f"ngram must be >= 1 (got {ngram})")
    if s0 < ngram:
        raise ValueError(
            f"prompt length {s0} shorter than the lookup ngram {ngram}"
        )
    if s0 + num_tokens + k > model.max_seq:
        raise ValueError(
            f"prompt {s0} + {num_tokens} tokens + k={k} speculative slack "
            f"exceeds max_seq {model.max_seq}"
        )
    _validate_spec_sampling(temperature, key, top_k, top_p, model.vocab)
    run = _compiled_lookup_run(model, s0, num_tokens, int(k), int(ngram),
                               str(jnp.dtype(cache_dtype)),
                               float(max(temperature, 0.0)), int(top_k),
                               float(top_p))
    if key is None:
        key = jax.random.key(0)  # unused at temperature 0
    toks, n_out, rounds = run(params, prompt, key)
    if return_stats:
        return toks, _spec_stats(n_out, rounds, num_tokens)
    return toks


def speculative_generate(
    model: TransformerLM,
    params,
    draft_model: TransformerLM,
    draft_params,
    prompt: jnp.ndarray,          # (1, S0) int32 — latency path, B = 1
    num_tokens: int,
    *,
    k: int = 4,
    cache_dtype="float32",
    temperature: float = 0.0,
    key: jax.Array | None = None,
    top_k: int = 0,
    top_p: float = 0.0,
    return_stats: bool = False,
):
    """Speculative decoding: a cheap draft proposes k-token chains, the
    target verifies each chain with ONE cached block forward
    (decode_block) — between 1 and k target-quality tokens per target
    forward.

    At temperature 0 (default) acceptance is exact argmax matching and
    the output is the target's own greedy continuation — the draft
    changes the speed, not the tokens. At temperature > 0 (key
    required; top_k/top_p as in generate()) acceptance is REJECTION
    SAMPLING: the draft samples its own filtered law q, the target
    accepts each proposal w.p. min(1, p/q) and replaces a reject with a
    residual sample — the emitted law is exactly plain temperature
    sampling's, for any draft (the speculative sampling theorem;
    distribution-equality tests in tests/test_spec_sampling.py).

    Precision caveat, stated exactly: decode_block's batched
    contractions may tile/reassociate differently from the plain decode
    scan's, so the two paths agree to float rounding (~1e-4 observed),
    not bitwise; a greedy argmax whose top-2 logits tie within that
    drift could in principle differ. The equality test
    (tests/test_generate.py) and the bench's in-run assert have never
    observed a divergence. Both models must share the vocab; the draft
    is typically shallower/narrower. B must be 1 (per-row acceptance
    lengths diverge in a batch; speculation is the latency lever, plain
    generate() the throughput one).

    Returns tokens (1, num_tokens) int32 — or (tokens, stats) with
    `return_stats=True`, where stats carries the verify-round count and
    the mean accepted tokens per round (capped at the returned budget —
    the final round's overshoot never lands in the buffer).
    """
    b, s0 = prompt.shape
    if b != 1:
        raise ValueError(f"speculative decoding is the B=1 latency path "
                         f"(got batch {b}); use generate() for batches")
    if num_tokens < 1:
        raise ValueError("num_tokens must be >= 1")
    if k < 2:
        raise ValueError(f"k must be >= 2 (k={k} would draft nothing)")
    if model.vocab != draft_model.vocab:
        raise ValueError(
            f"target vocab {model.vocab} != draft vocab {draft_model.vocab}"
        )
    if s0 + num_tokens + k > min(model.max_seq, draft_model.max_seq):
        raise ValueError(
            f"prompt {s0} + {num_tokens} tokens + k={k} speculative slack "
            f"exceeds max_seq (target {model.max_seq}, draft "
            f"{draft_model.max_seq}; BOTH caches hold every position)"
        )
    _validate_spec_sampling(temperature, key, top_k, top_p, model.vocab)
    run = _compiled_spec_run(model, draft_model, s0, num_tokens, int(k),
                             str(jnp.dtype(cache_dtype)),
                             float(max(temperature, 0.0)), int(top_k),
                             float(top_p))
    if key is None:
        key = jax.random.key(0)  # unused at temperature 0
    toks, n_out, rounds = run(params, draft_params, prompt, key)
    if return_stats:
        return toks, _spec_stats(n_out, rounds, num_tokens)
    return toks


def generate(
    model: TransformerLM,
    params,
    prompt: jnp.ndarray,          # (B, S0) int32
    num_tokens: int,
    *,
    temperature: float = 0.0,
    key: jax.Array | None = None,
    cache_dtype="float32",
    top_k: int = 0,
    top_p: float = 0.0,
):
    """Prefill the prompt (one batched forward), then sample `num_tokens`
    continuations with the KV-cached decode scan.

    Returns (B, num_tokens) int32. Greedy argmax at temperature 0,
    categorical sampling otherwise (key required), optionally restricted
    by `top_k` (k most likely) and/or `top_p` (nucleus: smallest set
    reaching mass p) — see filter_logits. Prompt length + num_tokens
    must fit max_seq. `cache_dtype` "bfloat16" halves the KV cache bytes
    decode reads per token (attention scores/softmax stay f32); f32 is
    the exactness default the parity tests pin.
    """
    b, s0 = prompt.shape
    if num_tokens < 1:
        raise ValueError("num_tokens must be >= 1")
    if s0 + num_tokens > model.max_seq:
        raise ValueError(
            f"prompt {s0} + {num_tokens} new tokens exceeds max_seq "
            f"{model.max_seq}"
        )
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if top_k < 0 or top_k > model.vocab:
        raise ValueError(f"top_k {top_k} not in [0, vocab {model.vocab}]")
    if not 0.0 <= top_p <= 1.0:
        raise ValueError(f"top_p {top_p} not in [0, 1]")
    if (top_k or top_p) and temperature <= 0:
        raise ValueError(
            "top_k/top_p restrict SAMPLING — set temperature > 0 "
            "(greedy argmax already takes the single most likely token)"
        )
    if key is None:
        key = jax.random.key(0)  # unused at temperature 0
    run = _compiled_run(model, s0, num_tokens, float(temperature),
                        str(jnp.dtype(cache_dtype)), int(top_k),
                        float(top_p))
    return run(params, prompt, key)
