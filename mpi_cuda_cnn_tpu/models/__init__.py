"""Models: functional layer API and benchmark presets."""

from .layers import AvgPool, Conv, Dense, Flatten, MaxPool, Sequential
from .initializers import get_initializer
from .presets import MODEL_PRESETS, get_model

__all__ = [
    "Conv",
    "Dense",
    "Flatten",
    "MaxPool",
    "AvgPool",
    "Sequential",
    "get_initializer",
    "MODEL_PRESETS",
    "get_model",
]
