"""Ops: the framework's compute primitives.

Two interchangeable implementations of each hot op:

- `xla` (this package's conv.py/dense.py): `lax.conv_general_dilated` / dot —
  the correctness oracle, and already MXU-optimal for these shapes.
- `pallas` (pallas_ops.py): hand-written TPU kernels, the twin of the
  reference's CUDA kernel surface (CUDAcnn.cu:167-218), wired in via
  custom_vjp.

Selection is per-model via `models.Sequential(..., backend=...)` or the
`--use-pallas` flag.
"""

from .activations import relu, softmax, stable_softmax, tanh
from .conv import conv2d, conv2d_input_grad, conv2d_kernel_grad
from .dense import dense
from .losses import softmax_cross_entropy, squared_error_total

__all__ = [
    "relu",
    "tanh",
    "softmax",
    "stable_softmax",
    "conv2d",
    "conv2d_input_grad",
    "conv2d_kernel_grad",
    "dense",
    "softmax_cross_entropy",
    "squared_error_total",
]
