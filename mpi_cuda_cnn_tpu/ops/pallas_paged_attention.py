"""Fused paged-attention decode kernel for TPU (Pallas).

The XLA formulation of the paged read (serve/paged_cache.py's gather
path) materializes every slot's gathered (B, L, Hkv, hd) cache rows in
HBM before `attend_kv` touches them — per layer, per tick. On a
bandwidth-bound decode tick (PERF.md decode table: tokens/s tracks
cache bytes almost linearly) that round-trip is pure waste: the pages
already hold the rows; only their ORDER is indirect. This kernel is the
FlashAttention discipline (ops/pallas_attention.py) applied to the
PagedAttention layout (Kwon et al., SOSP '23): consume the page pool +
block tables directly, stream each page HBM -> VMEM, and keep the
gathered rows on-chip until the attention output is done.

Shape contract (the one `paged_update_attend` already speaks):

- q: (B, kk, H, hd) — kk = 1 is the decode tick, kk = chunk the
  prefill chunk; H % Hkv == 0 (GQA/MQA served by the same head
  mapping as `attend_kv`'s reshape: query head h serves kv head
  h // (H // Hkv)).
- pages: per-layer dicts {k, v} of (num_pages, page_size, Hkv, hd)
  (+ f32 absmax scales {ks, vs} of (num_pages, page_size, Hkv, 1) for
  the int8 form — the cache's quantization contract, dequantized
  IN-KERNEL exactly as attend_kv applies it: a k-row's scale multiplies
  the logits after the QK dot, a v-row's folds into the probabilities
  before the PV dot).
- block_table: (B, npages) int32; positions: (B, kk) int32 — both ride
  as SCALAR PREFETCH (PrefetchScalarGridSpec), so the page index for
  every grid step is known before the kernel body runs and the Pallas
  pipeline emitter double-buffers the per-page VMEM copies: page i+1's
  DMA is in flight while page i folds. That pipeline IS the per-page
  async-copy/double-buffer structure — hand-rolled semaphores would
  re-implement what the grid already provides.

Grid: (B, Hkv, npages) with the page axis innermost/sequential; each
(slot, kv head) program accumulates its pages' QK logits into a VMEM
scratch strip ((g*kk, L) f32, L = npages * page_size) and the v rows
into a (L, hd) VMEM buffer, then computes the EXACT softmax + PV on the
final page step. Exact-not-online is deliberate: the parity gate is
BITWISE against the gather path in f32, and the online-softmax
rescaling form (exp(m_i - m_new) carries) is 1-2 ulp off a single
softmax by construction. A decode slot's extent is bounded by the block
table (engine max_len), so the strip + v buffer fit VMEM at serving
shapes ((g*kk + hd) * L * 4 bytes ~ 1.1 MB at L=2048, hd=128, kk=1);
the online form only pays off past VMEM extents the serving engine
never allocates.

Parity discipline (pinned by tests/test_paged_kernel.py, interpret
mode on CPU): f32 BITWISE vs the gather path across MHA/GQA/MQA and
kk in {1, chunk} — every contraction mirrors attend_kv's dimension
structure (the g*kk == 1 gemv cell uses the same sum-product form
attend_kv uses off-TPU, the one formulation XLA CPU emits identically
in both contexts); bf16/int8 within 1e-5 (same elementwise math,
reduction order differs by at most the page split). ON TPU that gemv
cell keeps the MXU dot on BOTH sides (attend_kv's backend switch
matches), so the banked MHA decode hot path never trades its batched
gemv for a VPU sum-product — the bitwise contract is scoped to where
it is tested, and the serving configurations (GQA/MQA, and any kk > 1)
never enter the cell at all.

TPU compile notes: blocks are (page_size, hd) slabs, so page_size >= 8
(f32) / 16 (bf16) / 32 (int8) avoids sublane padding; the scratch strip
is allocated at the table's full L regardless of a slot's live extent —
the gather baseline reads those same bytes, so kernel-on/off A/B is
byte-fair. Interpret mode (any non-TPU backend) runs the same kernel
through the Pallas interpreter — the tier-1 CPU suite executes exactly
this code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..obs.trace import annotate
from .attention import NEG_INF


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _run_kernel(kern, grid_spec, out_shape, operands):
    """The one pallas_call site — also the MCT007 producer the lint
    manifest declares for this module's hot driver (`paged_attend`)."""
    return pl.pallas_call(
        kern, grid_spec=grid_spec, out_shape=out_shape,
        interpret=_interpret(),
    )(*operands)


def _paged_kernel(tbl_ref, pos_ref, *refs, npages, page_size, gkk, kk,
                  int8):
    """One (slot, kv head, page) grid step.

    Pages stream innermost: step i folds page block_table[b, i]'s QK
    logits into the s_buf strip (columns [i*ps, (i+1)*ps)) and parks
    its v rows in v_buf; the last step masks, softmaxes, and contracts
    — the gathered rows never exist outside VMEM.
    """
    if int8:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, s_buf, v_buf, vs_buf \
            = refs
    else:
        q_ref, k_ref, v_ref, o_ref, s_buf, v_buf = refs
        vs_buf = None
    b = pl.program_id(0)
    i = pl.program_id(2)
    ps = page_size

    q = q_ref[0, 0]                                  # (g*kk, hd)
    hd = q.shape[1]
    kp = k_ref[0, :, 0, :]                           # (ps, hd)
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    kpf = kp.astype(jnp.float32) if int8 else kp
    if gkk == 1 and kpf.dtype == jnp.float32 and _interpret():
        # The single-query gemv cell OFF-TPU: mirror attend_kv's
        # sum-product QK — the one formulation XLA CPU emits
        # identically inside and outside a kernel (a dot here would
        # take the gemv emitter's accumulation order and land 1 ulp off
        # the gather path; the f32 gate is bitwise). On TPU both sides
        # keep the MXU dot (attend_kv's backend switch matches).
        s = (jnp.sum(q[0][:, None] * kpf.T, axis=0)
             * scale)[None, :]                       # (1, ps)
    else:
        s = jax.lax.dot_general(
            q, kpf, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                    # (g*kk, ps)
    if int8:
        # attend_kv's contract: the k-scale is constant along the
        # contracted head_dim, so it multiplies the LOGITS — same
        # elementwise order as the gather path (scale, then absmax).
        s = s * ks_ref[0, :, 0, :].reshape(1, ps)
        vs_buf[0, pl.ds(i * ps, ps)] = vs_ref[0, :, 0, :].reshape(ps)
    s_buf[:, pl.ds(i * ps, ps)] = s
    v_buf[pl.ds(i * ps, ps), :] = v_ref[0, :, 0, :]

    @pl.when(i == npages - 1)
    def _():
        L = npages * ps
        pos = pos_ref[b]                             # (kk,)
        key_idx = jax.lax.broadcasted_iota(jnp.int32, (kk, L), 1)
        mask = key_idx <= pos[:, None]               # (kk, L)
        g = gkk // kk
        mask_full = jnp.broadcast_to(
            mask[None], (g, kk, L)).reshape(gkk, L)
        logits = jnp.where(mask_full, s_buf[:], NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        vb = v_buf[:]
        if int8:
            pv = probs * vs_buf[0, :][None, :]
            vv = vb.astype(jnp.float32)
        else:
            pv = probs.astype(vb.dtype)
            vv = vb
        if gkk == 1 and vv.dtype == jnp.float32 and _interpret():
            # The single-query gemv cell OFF-TPU: mirror attend_kv's
            # sum-product PV (same backend switch — TPU keeps the MXU
            # dot on both sides; see attend_kv).
            o = jnp.sum(pv[0][:, None] * vv, axis=0)[None, :]
        else:
            o = jax.lax.dot_general(
                pv, vv, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        o_ref[0, 0] = o


def paged_attend(q, c, positions, block_table, page_size: int):
    """Fused paged-attention read over one layer's page pools.

    q: (B, kk, H, hd); c: the layer's page dict (k/v [+ ks/vs]);
    positions: (B, kk) absolute positions; block_table: (B, npages).
    Returns (B, kk, H*hd) f32 — the drop-in replacement for the gather
    + attend_kv pair in serve/paged_cache.paged_update_attend (same
    mask semantics: row j attends key positions <= positions[b, j];
    rows beyond a slot's written extent read whatever the pages hold,
    masked out of the softmax exactly as the gather path does).
    """
    b, kk, h, hd = q.shape
    hkv = c["k"].shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    g = h // hkv
    gkk = g * kk
    npages = block_table.shape[1]
    ps = page_size
    int8 = c["k"].dtype == jnp.int8
    # Head-group layout: (B, Hkv, g*kk, hd), rows g-major within a kv
    # head — the same (hkv, g) split attend_kv's reshape uses, so the
    # index maps stay pure picks (no div/mod: the Mosaic constraint
    # _gqa_maps documents).
    qg = q.reshape(b, kk, hkv, g, hd).transpose(0, 2, 3, 1, 4).reshape(
        b, hkv, gkk, hd)

    def q_map(b_, h_, i_, tbl, pos):
        return b_, h_, 0, 0

    def page_map(b_, h_, i_, tbl, pos):
        return tbl[b_, i_], 0, h_, 0

    in_specs = [
        pl.BlockSpec((1, 1, gkk, hd), q_map),
        pl.BlockSpec((1, ps, 1, hd), page_map),
        pl.BlockSpec((1, ps, 1, hd), page_map),
    ]
    operands = [block_table.astype(jnp.int32),
                positions.astype(jnp.int32), qg, c["k"], c["v"]]
    scratch = [
        pltpu.VMEM((gkk, npages * ps), jnp.float32),   # logits strip
        pltpu.VMEM((npages * ps, hd), c["v"].dtype),   # gathered v rows
    ]
    if int8:
        in_specs.append(pl.BlockSpec((1, ps, 1, 1), page_map))
        in_specs.append(pl.BlockSpec((1, ps, 1, 1), page_map))
        operands += [c["ks"], c["vs"]]
        scratch.append(pltpu.VMEM((1, npages * ps), jnp.float32))

    kern = functools.partial(
        _paged_kernel, npages=npages, page_size=ps, gkk=gkk, kk=kk,
        int8=int8,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, npages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, gkk, hd), q_map),
        scratch_shapes=scratch,
    )
    with annotate("ops.paged_attention"):
        out = _run_kernel(
            kern, grid_spec,
            jax.ShapeDtypeStruct((b, hkv, gkk, hd), jnp.float32),
            operands,
        )
    # (B, Hkv, g, kk, hd) -> (B, kk, H*hd): head order (hkv, g) matches
    # attend_kv's output reshape, so the two paths agree row-for-row.
    return out.reshape(b, hkv, g, kk, hd).transpose(0, 3, 1, 2, 4).reshape(
        b, kk, h * hd)
