"""Dense (fully-connected) op.

The reference's `Layer_feedForw_full` (cnn.c:113-152) is a per-output MAC
loop over all inputs plus bias, with tanh (hidden) or softmax (output)
applied by the same function; backward (cnn.c:154-173) accumulates
u_weights += dnet * x_prev and propagates errors. Here: one batched matmul
on the MXU; activation/softmax belong to the layer/loss, and backward is
`jax.grad` (or the Pallas custom_vjp twin).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..obs.trace import annotate


def dense(x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray | None = None,
          precision=None) -> jnp.ndarray:
    """x: (N, d_in); w: (d_in, d_out); b: (d_out,)."""
    with annotate("ops.dense"):
        y = jnp.dot(x, w, precision=precision)
        if b is not None:
            y = y + b
        return y
