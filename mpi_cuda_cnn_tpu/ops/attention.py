"""Scaled dot-product attention ops.

The reference has NO attention and no sequence axis anywhere (its op
universe is conv + FC + softmax, SURVEY.md §2.3-2.5 / §5.7) — these ops
exist because long-context support is a first-class capability of this
framework, not a parity item. They are the single-device oracles that the
sequence-parallel forms in parallel/sp.py (ring attention over 'seq' via
ppermute; Ulysses all-to-all head parallelism) are tested against.

Conventions: q/k/v are (B, S, H, D) — batch, sequence, heads, head_dim —
the layout whose S axis shards over the 'seq' mesh axis. Softmax is
max-subtracted (the same stabilization as ops/activations.stable_softmax,
cnn.c:125-143's trick) and, for the blockwise form, an *online* softmax:
running max m, running denominator l, running numerator o, renormalized
as each key/value block arrives — the algebra that makes ring attention
exact, not approximate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..obs.trace import annotate

NEG_INF = -1e30  # large-but-finite: keeps fully-masked rows NaN-free


def attention(q, k, v, *, causal: bool = False):
    """Full (quadratic) scaled dot-product attention — the oracle.

    q: (B, S, H, D); k/v: (B, S, Hkv, D) with H % Hkv == 0 — Hkv < H is
    grouped-query attention (each kv head serves H/Hkv query heads;
    Hkv == 1 is MQA). Returns (B, S, H, D), f32 accumulation.
    """
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    g = h // hkv
    with annotate("ops.attention"):
        qg = q.reshape(b, sq, hkv, g, d)
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
        logits = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            qi = jnp.arange(sq)[:, None]
            ki = jnp.arange(k.shape[1])[None, :]
            logits = jnp.where(ki <= qi, logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum(
            "bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        return out.reshape(b, sq, h, d).astype(q.dtype)


def repeat_kv(kv, n_heads: int):
    """Expand (B, S, Hkv, D) k/v to the full H query heads by repeating
    each kv head over its group — THE one definition of the grouping
    convention (query head qh reads kv head qh // (H/Hkv); group-major,
    matching the oracle's reshape and the flash kernels' index maps)."""
    hkv = kv.shape[2]
    if n_heads == hkv:
        return kv
    if n_heads % hkv:
        raise ValueError(f"heads {n_heads} not a multiple of kv heads {hkv}")
    return jnp.repeat(kv, n_heads // hkv, axis=2)


def rope(x, positions, *, base: float = 10000.0):
    """Rotary position embedding (rotate-half form) for x: (B, S, H, D).

    positions: (S,) absolute token positions — explicit, so sequence
    shards under SP pass their true global positions (pos_offset +
    arange, exactly like the learned table) — or (B, S) PER-ROW
    positions, the continuous-batching decode form (each serving slot
    sits at its own depth, so one batched forward spans many absolute
    positions; serve/engine.py). Angles are computed in f32 regardless
    of x.dtype (bf16 loses position precision past ~256); output
    returns in x.dtype. D must be even.
    """
    d = x.shape[-1]
    if d % 2:
        raise ValueError(f"rope needs an even head dim, got {d}")
    half = d // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)  # (half,)
    angles = positions.astype(jnp.float32)[..., None] * freqs
    # (S, 1, half) broadcasts over batch AND heads; (B, S, 1, half)
    # broadcasts over heads only — one expand serves both rank forms.
    cos = jnp.expand_dims(jnp.cos(angles), -2)
    sin = jnp.expand_dims(jnp.sin(angles), -2)
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return out.astype(x.dtype)


def _block_logits(q, k, scale):
    return jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale


def online_softmax_block(carry, q, k, v, mask=None):
    """Fold one key/value block into the online-softmax state.

    carry = (o, m, l):
      o: (B, Sq, H, D) f32 — running unnormalized numerator,
      m: (B, H, Sq)    f32 — running row max,
      l: (B, H, Sq)    f32 — running denominator.
    mask: optional (Sq, Sk) bool, True = attend.

    Returns the updated carry. Finalize with o / l (see finalize_online).
    This is the exact blockwise-softmax recurrence (numerically identical
    to full softmax for any block order that respects the mask).
    """
    o, m, l = carry
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    logits = _block_logits(q, k, scale)  # (B, H, Sq, Sk) f32
    if mask is not None:
        logits = jnp.where(mask[None, None, :, :], logits, NEG_INF)

    m_blk = jnp.max(logits, axis=-1)          # (B, H, Sq)
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)                # rescale of old state
    p = jnp.exp(logits - m_new[..., None])    # (B, H, Sq, Sk)
    if mask is not None:
        # A fully-masked row keeps m == m_new == NEG_INF, where
        # exp(logit - m_new) = exp(0) = 1 would silently count masked
        # keys; zero them so l stays 0 and finalize_online yields zeros.
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None]  # (B, Sq, H, 1) rescale
    o_new = o_new + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return o_new, m_new, l_new


def init_online(q):
    """Fresh online-softmax carry for queries q: (B, Sq, H, D)."""
    b, sq, h, d = q.shape
    o = jnp.zeros((b, sq, h, d), jnp.float32)
    m = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    return o, m, l


def finalize_online(carry, dtype):
    """o / l with fully-masked rows (l == 0) mapped to zeros."""
    o, m, l = carry
    l_t = l.transpose(0, 2, 1)[..., None]  # (B, Sq, H, 1)
    return jnp.where(l_t > 0, o / jnp.maximum(l_t, 1e-30), 0.0).astype(dtype)


def blockwise_attention(q, k, v, *, block_size: int, causal: bool = False):
    """Full attention computed block-by-block with the online softmax —
    the single-device form of the ring-attention math (memory O(S·block)
    for the logits instead of O(S²)). Exact parity with attention()."""
    b, s, h, d = q.shape
    if s % block_size:
        raise ValueError(f"seq len {s} not divisible by block {block_size}")
    nblk = s // block_size
    kb = k.reshape(b, nblk, block_size, h, d)
    vb = v.reshape(b, nblk, block_size, h, d)
    qi = jnp.arange(s)[:, None]

    def fold(carry, blk):
        kj, vj, j = blk
        ki = j * block_size + jnp.arange(block_size)[None, :]
        mask = (ki <= qi) if causal else jnp.ones((s, block_size), bool)
        return online_softmax_block(carry, q, kj, vj, mask), None

    carry, _ = jax.lax.scan(
        fold,
        init_online(q),
        (kb.transpose(1, 0, 2, 3, 4), vb.transpose(1, 0, 2, 3, 4),
         jnp.arange(nblk)),
    )
    return finalize_online(carry, q.dtype)
