"""Pallas TPU kernels — the accelerator-kernel surface of the framework.

The reference's native surface is one CUDA conv-forward kernel + host
wrapper (conv_forward_kernel CUDAcnn.cu:167-195, forward_convolution_layer
CUDAcnn.cu:198-218): one thread per output element, per-call
cudaMalloc/H2D/D2H round-trips, and no backward (conv bwd and all FC work
stayed on the CPU — SURVEY.md 2.14-2.15). These kernels close that gap the
TPU way:

- data stays HBM/VMEM-resident (no per-call host round-trip — the wrapper
  feeds device arrays straight to pallas_call);
- compute is phrased as MXU matmuls, not per-element threads: the direct
  conv is a sum over kernel positions of (batch*out_pixels, Cin) @
  (Cin, Cout) contractions accumulated in an f32 VMEM scratch;
- strided convs are decomposed space-to-batch style in the wrapper: a
  stride-s conv is the sum of s*s stride-1 convs over phase-shifted inputs
  with phase-sliced kernels (Mosaic vectors don't do strided extracts, and
  stride-1 is what the MXU formulation wants anyway); the phase slicing is
  zero-FLOP XLA glue, every MAC runs in the Pallas kernel;
- backward exists: d(input) reuses the SAME stride-1 forward kernel on the
  stride-dilated cotangent with the spatially-flipped, in/out-transposed
  kernel (the transposed-conv identity), and d(kernel) is its own
  batch-accumulating kernel (phase-decomposed the same way);
- everything is wired into jax.custom_vjp, so `jax.grad` of a model using
  backend="pallas" differentiates through these kernels.

On non-TPU backends the kernels run in Pallas interpreter mode, so the
whole suite is testable on the CPU mesh (tests/test_pallas.py checks
parity against the XLA oracle ops).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# Dense: tiled MXU matmul
# ---------------------------------------------------------------------------

_BM = 128  # rows per program (MXU-aligned)
_BN = 128  # cols per program


def _matmul_kernel(x_ref, w_ref, o_ref):
    o_ref[:] = jnp.dot(
        x_ref[:], w_ref[:], preferred_element_type=jnp.float32
    ).astype(o_ref.dtype)


def _matmul(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """(M, K) @ (K, N) on the MXU, tiled (BM, K)x(K, BN) per program.

    K is kept whole per program (our models' K <= ~4k: the (BM, K) and
    (K, BN) blocks fit VMEM comfortably); M and N are padded to tile
    multiples and sliced back.
    """
    m, k = x.shape
    _, n = w.shape
    mp, np_, kp = _round_up(m, _BM), _round_up(n, _BN), _round_up(k, 8)
    xp = jnp.pad(x, ((0, mp - m), (0, kp - k)))
    wp = jnp.pad(w, ((0, kp - k), (0, np_ - n)))
    out = pl.pallas_call(
        _matmul_kernel,
        grid=(mp // _BM, np_ // _BN),
        in_specs=[
            pl.BlockSpec((_BM, kp), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((kp, _BN), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (_BM, _BN), lambda i, j: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x.dtype),
        interpret=_interpret(),
    )(xp, wp)
    return out[:m, :n]


@jax.custom_vjp
def dense_pallas(x: jnp.ndarray, w: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """FC forward on the MXU: the Pallas twin of Layer_feedForw_full's MAC
    loop (cnn.c:113-123)."""
    return _matmul(x, w) + b


def _dense_fwd(x, w, b):
    return dense_pallas(x, w, b), (x, w)


def _dense_bwd(res, g):
    """FC backward (the Pallas twin of Layer_feedBack_full, cnn.c:154-173):
    dx = g @ w^T (error propagation), dw = x^T @ g (u_weights
    accumulation), db = sum(g)."""
    x, w = res
    g = g.astype(x.dtype)
    dx = _matmul(g, w.T)
    dw = _matmul(x.T, g)
    db = jnp.sum(g, axis=0)
    return dx, dw, db


dense_pallas.defvjp(_dense_fwd, _dense_bwd)


# ---------------------------------------------------------------------------
# Conv: stride-1 direct convolution kernels + space-to-batch wrappers
# ---------------------------------------------------------------------------


def _flatten_pixels(xs, m, cin):
    """(BN, OH, OW, Cin) window slice -> (BN*OH*OW, Cin) matmul operand.

    Packed dtypes (bf16) can't reshape across the sublane dim directly —
    Mosaic rejects e.g. vector<8x7x7x16xbf16> -> vector<392x16xbf16> — so
    the reshape goes through f32 (lossless for bf16) and casts back for
    the MXU."""
    if xs.dtype == jnp.float32:
        return xs.reshape(m, cin)
    return xs.astype(jnp.float32).reshape(m, cin).astype(xs.dtype)


def _conv1_kernel(x_ref, w_ref, o_ref, acc_ref, *, kh, kw, oh, ow):
    """One batch-tile of stride-1 valid direct conv.

    x_ref: (BN, Hp, Wp, Cin) block in VMEM, Hp >= oh+kh-1, Wp >= ow+kw-1.
    w_ref: (kh, kw, Cin, Cout) kernel.
    o_ref: (BN, OH, OW, Cout).
    For each kernel offset (ky, kx): unit-stride window slice, flatten
    pixels, accumulate an MXU contraction — the same arithmetic as the
    CUDA kernel's per-thread triple loop (CUDAcnn.cu:179-191), phrased as
    (BN*OH*OW, Cin) @ (Cin, Cout) matmuls.

    Index discipline: ky advances via fori_loop — a dynamic offset, legal
    because H is an untiled dim (so is w's kh) — while kx is a static
    Python unroll: dim 2 is the sublane dim, where Mosaic cannot prove
    alignment of dynamic offsets for packed dtypes (bf16's (16, 128)
    tiling). The loop also keeps at most kw window slices live at a time;
    with small cin the lane-padded slices are large, and unrolling all
    kh*kw of them overflows VMEM.
    """
    bn = x_ref.shape[0]
    cin = x_ref.shape[3]
    acc_ref[:] = jnp.zeros_like(acc_ref)

    def body(ky, _):
        for kx in range(kw):
            xs = _flatten_pixels(
                x_ref[:, pl.ds(ky, oh), kx : kx + ow, :], bn * oh * ow, cin
            )
            acc_ref[:] += jnp.dot(
                xs, w_ref[ky, kx], preferred_element_type=jnp.float32
            )
        return 0

    jax.lax.fori_loop(0, kh, body, 0)
    o_ref[:] = acc_ref[:].reshape(o_ref.shape).astype(o_ref.dtype)


def _pick_batch_tile(
    n, hp, wp, cin, oh, ow, cout, kw, itemsize, budget=8 * 2**20
) -> int:
    """Largest batch tile whose VMEM working set fits the scoped limit.

    Counts what actually occupies VMEM, with the (8, 128)
    sublane/lane padding Mosaic stores blocks with: the x and out blocks,
    up to kw+1 live f32 window slices (_flatten_pixels round-trips packed
    dtypes through f32, and the kx unroll keeps kw slices in flight), and
    the f32 accumulator. The naive 4*elements estimate under-counted
    lane padding ~8x for small channel counts and OOM'd the 16M scoped
    vmem on the bf16 backward."""
    lane = lambda c: -(-c // 128) * 128
    # Packed dtypes tile (16, 128), f32 (8, 128); >=4-byte dtypes all (8, 128).
    s_mult = 8 * max(4 // itemsize, 1)
    sub = lambda s: -(-s // s_mult) * s_mult
    per_sample = (
        hp * sub(wp) * lane(cin) * itemsize        # x block
        + (kw + 1) * oh * ow * lane(cin) * 4       # live window slices (f32)
        + oh * ow * lane(cout) * 4                 # f32 accumulator
        + oh * sub(ow) * lane(cout) * itemsize     # out / cotangent block
    )
    bn = max(1, min(n, budget // max(per_sample, 1)))
    while n % bn:
        bn -= 1
    return bn


def _conv1(x: jnp.ndarray, w: jnp.ndarray, oh: int, ow: int) -> jnp.ndarray:
    """Stride-1 valid conv via the Pallas kernel; x is already padded."""
    n, hp, wp, cin = x.shape
    kh, kw, _, cout = w.shape
    bn = _pick_batch_tile(n, hp, wp, cin, oh, ow, cout, kw, x.dtype.itemsize)
    kernel = functools.partial(_conv1_kernel, kh=kh, kw=kw, oh=oh, ow=ow)
    return pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec(
                (bn, hp, wp, cin), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (kh, kw, cin, cout),
                lambda i: (0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (bn, oh, ow, cout), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((n, oh, ow, cout), x.dtype),
        scratch_shapes=[pltpu.VMEM((bn * oh * ow, cout), jnp.float32)],
        interpret=_interpret(),
    )(x, w)


def _phases(xp, w, stride):
    """Space-to-batch decomposition: yield (phase input, phase kernel) pairs
    such that the stride-s conv of the original equals the SUM of stride-1
    valid convs of the pairs. The phase slicing is zero-FLOP XLA glue."""
    kh, kw = w.shape[0], w.shape[1]
    for ry in range(min(stride, kh)):
        for rx in range(min(stride, kw)):
            wk = w[ry::stride, rx::stride]
            px = xp[:, ry::stride, rx::stride, :]
            yield px, wk


def _conv_forward(x, w, stride: int, padding: int):
    n, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    oh = (h + 2 * padding - kh) // stride + 1
    ow = (wd + 2 * padding - kw) // stride + 1
    # Extra (stride-1) high-side zero pad so every phase grid is complete;
    # the zeros multiply kernel taps beyond the true extent and contribute 0.
    extra = stride - 1
    xp = jnp.pad(
        x,
        ((0, 0), (padding, padding + extra), (padding, padding + extra), (0, 0)),
    )
    if stride == 1:
        return _conv1(xp[:, : oh + kh - 1, : ow + kw - 1, :], w, oh, ow)
    out = None
    for px, wk in _phases(xp, w, stride):
        qh, qw = wk.shape[0], wk.shape[1]
        px = px[:, : oh + qh - 1, : ow + qw - 1, :]
        y = _conv1(px, wk, oh, ow)
        out = y if out is None else out + y
    return out


def _conv1_dw_kernel(x_ref, g_ref, dw_ref, *, kh, kw, oh, ow):
    """d(kernel) of a stride-1 valid conv for one batch tile, accumulated
    across the sequential grid: dw[ky,kx] = x_window^T @ g over all pixels —
    the Pallas twin of the reference's u_weights accumulation
    (cnn.c:238-242). Same index discipline as _conv1_kernel: dynamic ky on
    untiled dims, static kx on the sublane dim."""
    i = pl.program_id(0)
    bn = x_ref.shape[0]
    cin = x_ref.shape[3]
    cout = g_ref.shape[3]

    @pl.when(i == 0)
    def _():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    gf = _flatten_pixels(g_ref[:], bn * oh * ow, cout)

    def body(ky, _):
        for kx in range(kw):
            xs = _flatten_pixels(
                x_ref[:, pl.ds(ky, oh), kx : kx + ow, :], bn * oh * ow, cin
            )
            dw_ref[ky, kx] += jnp.dot(
                xs.T, gf, preferred_element_type=jnp.float32
            ).astype(dw_ref.dtype)
        return 0

    jax.lax.fori_loop(0, kh, body, 0)


def _conv1_dw(x, g, kh: int, kw: int):
    """dw for a stride-1 valid conv; x already padded/cropped to match g."""
    n, hp, wp, cin = x.shape
    _, oh, ow, cout = g.shape
    bn = _pick_batch_tile(n, hp, wp, cin, oh, ow, cout, kw, x.dtype.itemsize)
    kernel = functools.partial(_conv1_dw_kernel, kh=kh, kw=kw, oh=oh, ow=ow)
    dw = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec(
                (bn, hp, wp, cin), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (bn, oh, ow, cout), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (kh, kw, cin, cout),
            lambda i: (0, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((kh, kw, cin, cout), jnp.float32),
        interpret=_interpret(),
    )(x, g)
    return dw


def _conv_dw(x, g, stride: int, padding: int, kh: int, kw: int):
    n, h, wd, cin = x.shape
    _, oh, ow, cout = g.shape
    extra = stride - 1
    xp = jnp.pad(
        x,
        ((0, 0), (padding, padding + extra), (padding, padding + extra), (0, 0)),
    )
    if stride == 1:
        dw = _conv1_dw(xp[:, : oh + kh - 1, : ow + kw - 1, :], g, kh, kw)
        return dw.astype(x.dtype)
    dw = jnp.zeros((kh, kw, cin, cout), jnp.float32)
    for ry in range(min(stride, kh)):
        for rx in range(min(stride, kw)):
            qh = len(range(ry, kh, stride))
            qw = len(range(rx, kw, stride))
            px = xp[:, ry::stride, rx::stride, :][:, : oh + qh - 1, : ow + qw - 1, :]
            dphase = _conv1_dw(px, g, qh, qw)
            dw = dw.at[ry::stride, rx::stride].set(dphase)
    return dw.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv2d_pallas(x, w, stride: int = 1, padding: int = 0):
    """Direct conv forward — the TPU twin of conv_forward_kernel
    (CUDAcnn.cu:167-195). x: (N,H,W,Cin), w: (kh,kw,Cin,Cout)."""
    return _conv_forward(x, w, stride, padding)


def _conv_fwd(x, w, stride, padding):
    return _conv_forward(x, w, stride, padding), (x, w)


def _conv_bwd(stride, padding, res, g):
    """Conv backward — the piece the reference never wrote for its GPU path
    (conv bwd stayed CPU-only, SURVEY.md 2.15).

    dx: transposed conv = the SAME stride-1 forward kernel over the
    stride-dilated cotangent with flipped/in-out-transposed weights
    (cnn.c:228-236's scatter, re-expressed as a gather so it stays an MXU
    contraction). dw: the accumulating kernel above.
    """
    x, w = res
    kh, kw, cin, cout = w.shape
    n, h, wd, _ = x.shape
    g = g.astype(x.dtype)

    # Dilate the cotangent by the forward stride (XLA glue; zero FLOPs).
    if stride > 1:
        g_dil = lax.pad(
            g,
            jnp.zeros((), g.dtype),
            ((0, 0, 0), (0, 0, stride - 1), (0, 0, stride - 1), (0, 0, 0)),
        )
    else:
        g_dil = g
    # Pad so the stride-1 valid conv recovers the full (h, wd) input extent.
    ph = kh - 1 - padding
    pw = kw - 1 - padding
    eh = h - (g_dil.shape[1] + 2 * ph - kh + 1)
    ew = wd - (g_dil.shape[2] + 2 * pw - kw + 1)
    g_dil = jnp.pad(g_dil, ((0, 0), (ph, ph + eh), (pw, pw + ew), (0, 0)))
    w_t = jnp.transpose(w[::-1, ::-1, :, :], (0, 1, 3, 2))  # flip + swap io
    dx = _conv1(g_dil, w_t, h, wd)
    dw = _conv_dw(x, g, stride, padding, kh, kw)
    return dx, dw


conv2d_pallas.defvjp(_conv_fwd, _conv_bwd)
