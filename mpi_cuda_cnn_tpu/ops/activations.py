"""Activations matching the reference's semantics.

Reference definitions (cnn.c:46-57): relu(x)=max(x,0); relu_g(y)=(y>0);
tanh via libm with tanh_g(y)=1-y^2 — both gradient helpers take the
*activation value*, which is exactly what reverse-mode AD of these closed
forms produces, so `jax.grad` over these is the faithful backward.
Softmax is the max-subtracted stable form (cnn.c:125-143).
"""

from __future__ import annotations

import jax.numpy as jnp


def relu(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.maximum(x, 0)


def tanh(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.tanh(x)


def stable_softmax(logits: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Max-subtracted softmax — numerically identical in structure to the
    reference's loop at cnn.c:125-143 (find max, exp-shift, normalize)."""
    shifted = logits - jnp.max(logits, axis=axis, keepdims=True)
    e = jnp.exp(shifted)
    return e / jnp.sum(e, axis=axis, keepdims=True)


softmax = stable_softmax

ACTIVATIONS = {
    "relu": relu,
    "tanh": tanh,
    "linear": lambda x: x,
    None: lambda x: x,
}
