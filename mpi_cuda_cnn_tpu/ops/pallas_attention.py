"""Fused flash-attention forward kernel for TPU (Pallas).

The jnp-level `blockwise_attention` (ops/attention.py) already has the
right algorithm — online softmax over key/value blocks — but materializes
each (S, block) logit slab in HBM-visible intermediates and leans on XLA
to fuse. This kernel is the fused form: one Pallas program per
(batch*head, q-block) computes its whole output tile with the logits
living only in registers/VMEM — O(BLK_Q * BLK_K) live logits instead of
O(S^2) — and the (m, l, acc) online-softmax carry never leaves VMEM.

Layout: q/k/v arrive (B, S, H, D) (the framework's SP-friendly layout),
kernel works on (B*H, S, D) over a (batch*head, q-block, k-block) grid —
the k-block axis is innermost/sequential and the carry persists in VMEM
scratch, so VMEM stays O(BLK) regardless of S (32k+ context on one chip).
GQA (k/v with Hkv < H heads) switches to a 5-D (b, hkv, group, q-block,
k-block) grid whose index maps are pure mul/add — each kv head serves
its query group zero-copy, and no map ever needs div/mod on a grid
coordinate.
Compute is (BLK_Q, D) @ (D, BLK_K) MXU contractions with f32 accumulators.
Dtype policy: f32 inputs run at HIGHEST precision (~1e-6 vs a float64
reference — the default-precision XLA oracle sits at ~1e-2); bf16 inputs
stay bf16 operands on the MXU's native bf16 x bf16 -> f32 path (~4x the
f32 matmul throughput — the training configuration), with the softmax,
online-carry, and output accumulation still f32. Causal masking uses 2-D
broadcasted_iota and skips blocks fully above the diagonal.

Backward: fused too — a dq kernel (q-rows outer, k-blocks streamed) and a
dk/dv kernel (k-rows outer, q-blocks streamed), with the softmax
probabilities reconstructed exactly from the forward's saved per-row
logsumexp (p = exp(s - L); causal masking falls out as exp(NEG_INF - L)
= 0). O(block) memory end to end; gradient accuracy ~4e-5 of a float64
reference on TPU (PERF.md). The reference never wrote ANY attention
(SURVEY.md §5.7) — this kernel exists for the framework's long-context
path, as the fused twin of ops/attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..obs.trace import annotate
from .attention import NEG_INF

# Tuned on v5e (s=8192, d=64): large blocks amortize per-grid-step
# overhead; (512, 1024) ran ~1.5x faster than the XLA oracle at equal
# (HIGHEST) precision, and ~2x larger blocks exhaust scoped VMEM.
BLK_Q = 512
BLK_K = 1024
# bf16 operands halve the VMEM per element: (1024, 1024) fits and runs
# ~25% faster than (512, 1024) (measured s=2048: 2.69 vs 3.64 ms fwd;
# s=8192: 4.6 vs 5.9). (2048, 2048) exhausts VMEM and fails to compile.
BLK_Q_BF16 = 1024
BLK_K_BF16 = 1024


def _blocks(dtype) -> tuple[int, int]:
    if dtype == jnp.bfloat16:
        return BLK_Q_BF16, BLK_K_BF16
    return BLK_Q, BLK_K


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dot(a, b, dims, hi: bool):
    """MXU contraction with f32 accumulation. hi=True adds HIGHEST
    precision — right for f32 inputs (the kernel's original accuracy
    contract); for bf16 inputs the default precision IS the native
    bf16 x bf16 -> f32 MXU path (~4x the f32 throughput), and HIGHEST
    would force f32 upconversion passes."""
    return jax.lax.dot_general(
        a, b, (dims, ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST if hi else None,
    )


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, causal, nk, scale, pid=(1, 2)
):
    """One (batch*head, q-block, k-block) grid step.

    The k-block axis is the INNERMOST grid dim — sequential on TPU — and
    the online-softmax carry (acc, m, l) lives in VMEM scratch that
    persists across those steps: init at kj == 0, fold one (BLK_Q, BLK_K)
    tile, write the normalized output at kj == nk - 1. K/V blocks are
    (BLK_K, D) — VMEM stays O(BLK) regardless of S.
    """
    qi = pl.program_id(pid[0])
    kj = pl.program_id(pid[1])
    q = q_ref[0]                                   # (BLK_Q, D)
    blk_q, d = q.shape
    blk_k = k_ref.shape[1]

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    hi = q_ref.dtype == jnp.float32

    def fold():
        s = _dot(q, k_ref[0], ((1,), (1,)), hi) * scale  # (BLK_Q, BLK_K)
        if causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0
            )
            kpos = kj * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1
            )
            mask = kpos <= qpos
            s = jnp.where(mask, s, NEG_INF)
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        if causal:
            # Fully-masked rows keep m == NEG_INF where exp(0) = 1 would
            # count masked keys; zero them so l stays 0.
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_ref[:, :1] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, :1] = m_new
        # p rounds to the input dtype for the PV contraction (exact for
        # f32; the standard flash-attention practice for bf16 — the MXU
        # takes bf16 operands, the accumulator stays f32).
        acc_ref[:] = acc_ref[:] * alpha + _dot(
            p.astype(v_ref.dtype), v_ref[0], ((1,), (0,)), hi
        )

    if causal:
        # Blocks fully above the diagonal contribute nothing: skip them
        # (they still iterate — the win is skipped FLOPs, ~2x).
        pl.when(kj * blk_k <= qi * blk_q + blk_q - 1)(fold)
    else:
        fold()

    @pl.when(kj == nk - 1)
    def _():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # Per-row logsumexp, saved for the fused backward: p can be
        # reconstructed exactly as exp(s - L) without re-running the
        # online recurrence. Stored (1, 8, blk_q) — the sublane dim is
        # padded to 8 because Pallas blocks need (8, 128)-divisible tails.
        lse = m_ref[:, 0] + jnp.log(l[:, 0])
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


def _pick_block(s: int, cap: int) -> int:
    """Largest multiple of 128 that divides s, capped at `cap`."""
    b = min(cap, s)
    b -= b % 128
    while b > 128 and s % b:
        b -= 128
    return b


def _to_rows(t, b, h, s, d):
    return t.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_rows(t, b, h, s, d):
    return t.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _gqa_maps(h: int, hkv: int):
    """Index maps for the GQA 5-D grid (b, hkv, g, blkA, blkB): query
    rows live at b*H + kvh*g + gi, kv rows at b*Hkv + kvh — all mul/add
    (a fused (b*H,) grid would need div/mod in the maps, which Mosaic
    compiles pathologically slowly at large grids: measured minutes-long
    hangs at s >= 8192). blkA/blkB pick their grid coordinate per kernel
    via the returned lambdas' last two axes."""
    g = h // hkv

    def q_rows(axis):  # row from (b, kvh, gi); seq block from grid[axis]
        def index_map(b, kvh, gi, i, j):
            return b * h + kvh * g + gi, (i if axis == 3 else j), 0
        return index_map

    def kv_rows(axis):
        def index_map(b, kvh, gi, i, j):
            return b * hkv + kvh, (i if axis == 3 else j), 0
        return index_map

    def lse_rows(axis):  # (rows, 8, s) layout: block index in slot 2
        def index_map(b, kvh, gi, i, j):
            return b * h + kvh * g + gi, 0, (i if axis == 3 else j)
        return index_map

    return q_rows, kv_rows, lse_rows


def _flash_forward(q, k, v, causal: bool, *, with_lse: bool = False,
                   out_f32: bool = False):
    """out_f32 keeps the f32 kernel output uncast — for callers (the
    ring-flash fold) that merge partials in f32; casting each per-hop
    partial to a bf16 input dtype would accumulate truncation error.

    GQA: k/v may carry Hkv < H heads (H % Hkv == 0); the kernel reads
    each kv head for its query-head group via the block index map."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    if s % 128:
        raise ValueError(f"seq len {s} must be a multiple of 128")
    orig_dtype = q.dtype
    bq, bk = _blocks(orig_dtype)
    blk_q = _pick_block(s, bq)
    blk_k = _pick_block(s, bk)
    # bf16 inputs stay bf16 into the kernel (native MXU operands, f32
    # accumulators/softmax inside — ~4x the f32 matmul throughput);
    # anything else computes in f32 at HIGHEST precision (the original
    # accuracy contract: ~1e-6 of a float64 reference).
    kdt = jnp.bfloat16 if orig_dtype == jnp.bfloat16 else jnp.float32
    qr = _to_rows(q.astype(kdt), b, h, s, d)
    kr = _to_rows(k.astype(kdt), b, hkv, s, d)
    vr = _to_rows(v.astype(kdt), b, hkv, s, d)

    nk = s // blk_k
    if hkv == h:
        grid = (b * h, s // blk_q, nk)
        pid = (1, 2)
        q_map = lambda bh, i, j: (bh, i, 0)
        kvm = lambda bh, i, j: (bh, j, 0)
        lse_map = lambda bh, i, j: (bh, 0, i)
    else:
        g_ = h // hkv
        grid = (b, hkv, g_, s // blk_q, nk)
        pid = (3, 4)
        q_rows, kv_rows, lse_rows = _gqa_maps(h, hkv)
        q_map = q_rows(3)
        kvm = kv_rows(4)
        lse_map = lse_rows(3)
    kernel = functools.partial(
        _flash_kernel, causal=causal, nk=nk, scale=1.0 / (d ** 0.5),
        pid=pid,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, d), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, d), kvm, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, d), kvm, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, d), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, blk_q), lse_map, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, 8, s), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, d), jnp.float32),    # acc
            pltpu.VMEM((blk_q, 128), jnp.float32),  # running max (col 0)
            pltpu.VMEM((blk_q, 128), jnp.float32),  # running denom (col 0)
        ],
        interpret=_interpret(),
    )(qr, kr, vr)
    out = _from_rows(out, b, h, s, d)
    if not out_f32:
        out = out.astype(orig_dtype)
    return (out, lse[:, 0, :]) if with_lse else out


# ---------------------------------------------------------------------------
# Fused backward: dq kernel (rows x streamed k-blocks) + dk/dv kernel
# (k-rows x streamed q-blocks). p is reconstructed exactly from the saved
# logsumexp (p = exp(s - L)); causal masking falls out of s = NEG_INF ->
# p = 0 with finite L. All accumulators live in VMEM scratch: O(block)
# memory, like the forward.
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref, dq_ref, acc_ref,
    *, causal, nk, scale, pid=(1, 2)
):
    qi = pl.program_id(pid[0])
    kj = pl.program_id(pid[1])
    q = q_ref[0]
    blk_q, d = q.shape
    blk_k = k_ref.shape[1]

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    hi = q_ref.dtype == jnp.float32

    def fold():
        s = _dot(q, k_ref[0], ((1,), (1,)), hi) * scale
        if causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = kj * blk_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        # lse/dvec arrive column-oriented: (1, blk_q, 8) with the row
        # value replicated along the narrow lane dim; [:, :1] is the
        # (blk_q, 1) column.
        p = jnp.exp(s - lse_ref[0][:, :1])
        dov = _dot(do_ref[0], v_ref[0], ((1,), (1,)), hi)
        ds = p * (dov - dvec_ref[0][:, :1]) * scale
        acc_ref[:] += _dot(ds.astype(k_ref.dtype), k_ref[0], ((1,), (0,)), hi)

    if causal:
        pl.when(kj * blk_k <= qi * blk_q + blk_q - 1)(fold)
    else:
        fold()

    @pl.when(kj == nk - 1)
    def _():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, causal, nq, scale, pid=(1, 2)
):
    ki = pl.program_id(pid[0])
    qj = pl.program_id(pid[1])
    k = k_ref[0]
    blk_k, d = k.shape
    blk_q = q_ref.shape[1]

    @pl.when(qj == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    hi = q_ref.dtype == jnp.float32

    def fold():
        # Transposed tile: rows = this program's keys, lanes = queries.
        s_t = _dot(k, q_ref[0], ((1,), (1,)), hi) * scale  # (blk_k, blk_q)
        if causal:
            kpos = ki * blk_k + jax.lax.broadcasted_iota(jnp.int32, s_t.shape, 0)
            qpos = qj * blk_q + jax.lax.broadcasted_iota(jnp.int32, s_t.shape, 1)
            s_t = jnp.where(kpos <= qpos, s_t, NEG_INF)
        # lse/dvec arrive lane-oriented: (1, 8, blk_q); row 0 of the
        # sublane padding is the (blk_q,) lane vector.
        p_t = jnp.exp(s_t - lse_ref[0, 0, :][None, :])
        dv_acc[:] += _dot(p_t.astype(do_ref.dtype), do_ref[0], ((1,), (0,)), hi)
        vdo = _dot(v_ref[0], do_ref[0], ((1,), (1,)), hi)  # (blk_k, blk_q)
        ds_t = p_t * (vdo - dvec_ref[0, 0, :][None, :]) * scale
        dk_acc[:] += _dot(ds_t.astype(q_ref.dtype), q_ref[0], ((1,), (0,)), hi)

    if causal:
        # Queries strictly before this key block are fully masked.
        pl.when(qj * blk_q + blk_q - 1 >= ki * blk_k)(fold)
    else:
        fold()

    @pl.when(qj == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal: bool, *, grads_f32: bool = False):
    """grads_f32 keeps the f32 kernel gradients uncast — for callers (the
    ring-flash backward) that ACCUMULATE partials across hops in f32;
    rounding each per-hop partial to a bf16 input dtype first would
    collect p truncation errors instead of one."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    bq, bk = _blocks(q.dtype)
    blk_q = _pick_block(s, bq)
    blk_k = _pick_block(s, bk)
    scale = 1.0 / (d ** 0.5)
    # Same dtype policy as the forward: bf16 operands stay bf16 into the
    # kernels (native MXU path), everything else f32 at HIGHEST.
    kdt = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32
    qr, orr, gr = (
        _to_rows(t.astype(kdt), b, h, s, d) for t in (q, o, g)
    )
    kr = _to_rows(k.astype(kdt), b, hkv, s, d)
    vr = _to_rows(v.astype(kdt), b, hkv, s, d)
    # D_i = rowsum(dO_i * O_i) — elementwise, O(S*D), always f32.
    dvec = jnp.sum(
        gr.astype(jnp.float32) * orr.astype(jnp.float32), axis=-1
    )                                                # (b*h, s)
    # Two orientations of the per-row vectors, so neither kernel pays a
    # sublane<->lane relayout: columns for the dq kernel, lanes for the
    # dk/dv kernel. Both are NARROW (8-wide minor dim, not 128): the
    # kernels only read lane/sublane 0, so HBM holds 8 replicas (the f32
    # sublane tile) instead of a full 128-lane broadcast — 16x less HBM
    # footprint/bandwidth for these side inputs; Mosaic lane-pads the
    # (blk_q, 8) tile on load.
    lse_col = jnp.broadcast_to(lse[:, :, None], (b * h, s, 8))
    dvec_col = jnp.broadcast_to(dvec[:, :, None], (b * h, s, 8))
    lse_row = jnp.broadcast_to(lse[:, None, :], (b * h, 8, s))
    dvec_row = jnp.broadcast_to(dvec[:, None, :], (b * h, 8, s))

    # Grid layout mirrors the forward: 3-D per-(b*h) for MHA; a 5-D
    # (b, hkv, g, blkA, blkB) grid for GQA so every index map stays
    # mul/add (div/mod in maps stalls Mosaic's compile at large grids).
    if hkv == h:
        dq_grid = (b * h, s // blk_q, s // blk_k)
        kv_grid = (b * h, s // blk_k, s // blk_q)
        pid = (1, 2)
        q_map = lambda bh, i, j: (bh, i, 0)
        q_stream_map = lambda bh, i, j: (bh, j, 0)
        kv_map = q_stream_map
        kv_row_map = q_map
        rows_map = lambda bh, i, j: (bh, 0, j)
    else:
        g_ = h // hkv
        dq_grid = (b, hkv, g_, s // blk_q, s // blk_k)
        kv_grid = (b, hkv, g_, s // blk_k, s // blk_q)
        pid = (3, 4)
        q_rows, kv_rows, lse_rows = _gqa_maps(h, hkv)
        q_map = q_rows(3)         # q/dq rows, block from grid[3]
        q_stream_map = q_rows(4)  # q/do streamed on grid[4] (dkv kernel)
        kv_map = kv_rows(4)       # k/v streamed on grid[4] (dq kernel)
        kv_row_map = kv_rows(3)   # k/v rows on grid[3] (dkv kernel)
        rows_map = lse_rows(4)

    q_spec = pl.BlockSpec((1, blk_q, d), q_map, memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((1, blk_q, 8), q_map, memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, blk_k, d), kv_map, memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, nk=s // blk_k,
                          scale=scale, pid=pid),
        grid=dq_grid,
        in_specs=[q_spec, k_spec, k_spec, q_spec, col_spec, col_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((blk_q, d), jnp.float32)],
        interpret=_interpret(),
    )(qr, kr, vr, gr, lse_col, dvec_col)

    # dk/dv: k-rows outer, q-blocks streamed innermost. The grid stays
    # per QUERY head; under GQA each kv head's gradient is produced as
    # H/Hkv per-qhead partial rows (racing writes to one shared kv row
    # are not expressible) and group-summed after the kernel — the
    # OUTPUT rows therefore index by query head in both layouts.
    kq_in_spec = pl.BlockSpec((1, blk_k, d), kv_row_map,
                              memory_space=pltpu.VMEM)
    # Output rows index by QUERY head with the block on grid[3] — which
    # is exactly q_map in both layouts (MHA: q rows == kv rows).
    kq_out_spec = pl.BlockSpec((1, blk_k, d), q_map,
                               memory_space=pltpu.VMEM)
    qs_spec = pl.BlockSpec((1, blk_q, d), q_stream_map,
                           memory_space=pltpu.VMEM)
    rows_spec = pl.BlockSpec((1, 8, blk_q), rows_map,
                             memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, nq=s // blk_q,
                          scale=scale, pid=pid),
        grid=kv_grid,
        in_specs=[qs_spec, kq_in_spec, kq_in_spec, qs_spec, rows_spec,
                  rows_spec],
        out_specs=[kq_out_spec, kq_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, s, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, d), jnp.float32),
            pltpu.VMEM((blk_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(qr, kr, vr, gr, lse_row, dvec_row)

    dq = _from_rows(dq, b, h, s, d)
    if hkv == h:
        dk = _from_rows(dk, b, h, s, d)
        dv = _from_rows(dv, b, h, s, d)
    else:
        # Sum the per-qhead partials within each kv group: rows are
        # ordered b*H with H = Hkv * group, group-major within a batch.
        g_ = h // hkv
        dk = _from_rows(
            dk.reshape(b, hkv, g_, s, d).sum(axis=2).reshape(b * hkv, s, d),
            b, hkv, s, d,
        )
        dv = _from_rows(
            dv.reshape(b, hkv, g_, s, d).sum(axis=2).reshape(b * hkv, s, d),
            b, hkv, s, d,
        )
    return tuple(
        t.astype(jnp.float32 if grads_f32 else ref.dtype)
        for t, ref in ((dq, q), (dk, k), (dv, v))
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal: bool = False):
    """Fused scaled-dot-product attention. q: (B, S, H, D); k/v:
    (B, S, Hkv, D) with H % Hkv == 0 (Hkv < H = grouped-query attention,
    served zero-copy via the kernel's block index maps). S a multiple of
    128. Exact (online softmax), causal optional. Both the forward and
    backward are fused Pallas kernels with O(block) memory."""
    with annotate("ops.flash_attention"):
        return _flash_forward(q, k, v, causal)


def _fwd(q, k, v, causal):
    out, lse = _flash_forward(q, k, v, causal, with_lse=True)
    return out, (q, k, v, out, lse)


def _bwd(causal, res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal)


flash_attention.defvjp(_fwd, _bwd)
