"""Fused flash-attention forward kernel for TPU (Pallas).

The jnp-level `blockwise_attention` (ops/attention.py) already has the
right algorithm — online softmax over key/value blocks — but materializes
each (S, block) logit slab in HBM-visible intermediates and leans on XLA
to fuse. This kernel is the fused form: one Pallas program per
(batch*head, q-block) computes its whole output tile with the logits
living only in registers/VMEM — O(BLK_Q * BLK_K) live logits instead of
O(S^2) — and the (m, l, acc) online-softmax carry never leaves VMEM.

Layout: q/k/v arrive (B, S, H, D) (the framework's SP-friendly layout),
kernel works on (B*H, S, D) over a (batch*head, q-block, k-block) grid —
the k-block axis is innermost/sequential and the carry persists in VMEM
scratch, so VMEM stays O(BLK) regardless of S (32k+ context on one chip).
Compute is (BLK_Q, D) @ (D, BLK_K) MXU contractions at HIGHEST precision
(~1e-6 vs a float64 reference — the default-precision XLA oracle sits at
~1e-2). f32 in-kernel (packed-dtype sublane slicing needs the conv-kernel
treatment; bf16 casts at the boundary). Causal masking uses 2-D
broadcasted_iota and skips blocks fully above the diagonal.

Backward: custom_vjp recomputes attention with the XLA oracle and
differentiates that — correct gradients (tested), O(S^2) bwd memory; a
fused Pallas backward is future work. The reference never wrote ANY
attention (SURVEY.md §5.7) — this kernel exists for the framework's
long-context path, as the fused twin of ops/attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_INF, attention

# Tuned on v5e (s=8192, d=64): large blocks amortize per-grid-step
# overhead; (512, 1024) ran ~1.5x faster than the XLA oracle at equal
# (HIGHEST) precision, and ~2x larger blocks exhaust scoped VMEM.
BLK_Q = 512
BLK_K = 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, causal, nk, scale
):
    """One (batch*head, q-block, k-block) grid step.

    The k-block axis is the INNERMOST grid dim — sequential on TPU — and
    the online-softmax carry (acc, m, l) lives in VMEM scratch that
    persists across those steps: init at kj == 0, fold one (BLK_Q, BLK_K)
    tile, write the normalized output at kj == nk - 1. K/V blocks are
    (BLK_K, D) — VMEM stays O(BLK) regardless of S.
    """
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    q = q_ref[0]                                   # (BLK_Q, D)
    blk_q, d = q.shape
    blk_k = k_ref.shape[1]

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    def fold():
        s = jax.lax.dot_general(
            q, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        ) * scale                                   # (BLK_Q, BLK_K)
        if causal:
            qpos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0
            )
            kpos = kj * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1
            )
            mask = kpos <= qpos
            s = jnp.where(mask, s, NEG_INF)
        m = m_ref[:, :1]
        l = l_ref[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        if causal:
            # Fully-masked rows keep m == NEG_INF where exp(0) = 1 would
            # count masked keys; zero them so l stays 0.
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_ref[:, :1] = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, :1] = m_new
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )

    if causal:
        # Blocks fully above the diagonal contribute nothing: skip them
        # (they still iterate — the win is skipped FLOPs, ~2x).
        pl.when(kj * blk_k <= qi * blk_q + blk_q - 1)(fold)
    else:
        fold()

    @pl.when(kj == nk - 1)
    def _():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _pick_block(s: int, cap: int) -> int:
    """Largest multiple of 128 that divides s, capped at `cap`."""
    b = min(cap, s)
    b -= b % 128
    while b > 128 and s % b:
        b -= 128
    return b


def _flash_forward(q, k, v, causal: bool):
    b, s, h, d = q.shape
    if s % 128:
        raise ValueError(f"seq len {s} must be a multiple of 128")
    blk_q = _pick_block(s, BLK_Q)
    blk_k = _pick_block(s, BLK_K)
    orig_dtype = q.dtype
    # f32 in the kernel: packed-dtype (bf16) sublane slicing needs extra
    # alignment work; numerics match the oracle's f32 accumulation anyway.
    qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
    to_rows = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    qr, kr, vr = to_rows(qf), to_rows(kf), to_rows(vf)

    nk = s // blk_k
    kernel = functools.partial(
        _flash_kernel, causal=causal, nk=nk, scale=1.0 / (d ** 0.5)
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s // blk_q, nk),
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda bh, i, j: (bh, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, d), lambda bh, i, j: (bh, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_k, d), lambda bh, i, j: (bh, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, blk_q, d), lambda bh, i, j: (bh, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((blk_q, d), jnp.float32),    # acc
            pltpu.VMEM((blk_q, 128), jnp.float32),  # running max (col 0)
            pltpu.VMEM((blk_q, 128), jnp.float32),  # running denom (col 0)
        ],
        interpret=_interpret(),
    )(qr, kr, vr)
    return (
        out.reshape(b, h, s, d).transpose(0, 2, 1, 3).astype(orig_dtype)
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal: bool = False):
    """Fused scaled-dot-product attention. q/k/v: (B, S, H, D), S a
    multiple of 128. Exact (online softmax), causal optional."""
    return _flash_forward(q, k, v, causal)


def _fwd(q, k, v, causal):
    return _flash_forward(q, k, v, causal), (q, k, v)


def _bwd(causal, res, g):
    # Recompute-and-differentiate via the XLA oracle: correct, O(S^2)
    # bwd memory (documented limitation; fused bwd kernel is future work).
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: attention(q, k, v, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
