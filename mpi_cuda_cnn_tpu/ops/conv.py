"""2-D convolution via XLA (`lax.conv_general_dilated`).

The reference implements direct convolution as a 6-deep C loop nest
(`Layer_feedForw_conv` cnn.c:175-210, backward cnn.c:212-247) and one CUDA
forward kernel (CUDAcnn.cu:167-195). Semantics reproduced here:

- zero padding via bounds check (cnn.c:191,196)  -> explicit XLA padding
- stride from the layer config (cnn.c:36-40)     -> window_strides
- weights shared per (out-ch, in-ch, ky, kx)     -> ordinary conv weights
- bias per output channel, activation fused      -> handled by the caller

Layouts are TPU-idiomatic NHWC/HWIO (channel minor → lane dimension), not
the reference's CHW/OIHW. The input/kernel gradient ops below mirror what
`jax.grad` of conv2d produces; they exist as named primitives so the Pallas
backward kernels have an oracle to test against (SURVEY.md §7 stage 4).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..obs.trace import annotate

DIMSPEC = ("NHWC", "HWIO", "NHWC")


def conv2d(
    x: jnp.ndarray,
    w: jnp.ndarray,
    *,
    stride: int | tuple[int, int] = 1,
    padding: int | tuple[int, int] = 0,
    precision=None,
) -> jnp.ndarray:
    """x: (N,H,W,Cin) f32/bf16; w: (kh,kw,Cin,Cout). Returns (N,Ho,Wo,Cout)."""
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    with annotate("ops.conv2d"):
        return lax.conv_general_dilated(
            x,
            w,
            window_strides=(sh, sw),
            padding=((ph, ph), (pw, pw)),
            dimension_numbers=DIMSPEC,
            precision=precision,
        )


@partial(jax.jit, static_argnames=("stride", "padding", "input_hw"))
def conv2d_input_grad(g, w, *, stride, padding, input_hw):
    """d(loss)/d(input) given cotangent g: transposed conv.

    Named twin of the dx half of the reference's conv backward
    (cnn.c:228-236: scatter of delta through the kernel into prev errors).
    """
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    kh, kw = w.shape[0], w.shape[1]
    ih, iw = input_hw
    oh, ow = g.shape[1], g.shape[2]
    # Transposed conv: dilate g by stride, correlate with spatially-flipped,
    # in/out-transposed kernel, with padding chosen to recover (ih, iw).
    pad_h = kh - 1 - ph
    pad_w = kw - 1 - pw
    extra_h = ih - ((oh - 1) * sh + kh - 2 * ph)
    extra_w = iw - ((ow - 1) * sw + kw - 2 * pw)
    w_t = jnp.transpose(w[::-1, ::-1, :, :], (0, 1, 3, 2))
    return lax.conv_general_dilated(
        g,
        w_t,
        window_strides=(1, 1),
        padding=((pad_h, pad_h + extra_h), (pad_w, pad_w + extra_w)),
        lhs_dilation=(sh, sw),
        dimension_numbers=DIMSPEC,
    )


@partial(jax.jit, static_argnames=("stride", "padding"))
def conv2d_kernel_grad(x, g, *, stride, padding):
    """d(loss)/d(kernel) given input x and cotangent g.

    Named twin of the dw half of the reference's conv backward
    (cnn.c:238-242: u_weights += delta * input patch). Expressed as a
    conv over the batch dimension (x as NCHW-style lhs with N as channels).
    """
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    # lhs: treat batch as contraction channel; rhs: cotangent as kernel.
    return lax.conv_general_dilated(
        jnp.transpose(x, (3, 1, 2, 0)),      # (Cin, H, W, N)
        jnp.transpose(g, (1, 2, 0, 3)),      # (Ho, Wo, N, Cout)
        window_strides=(1, 1),
        padding=((ph, ph), (pw, pw)),
        rhs_dilation=(sh, sw),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ).transpose(1, 2, 0, 3)                  # (kh, kw, Cin, Cout)
