"""Losses and training metrics.

The reference never writes a loss function: it seeds the backward pass with
`errors = outputs - onehot` after a softmax forward (cnn.c:284-286 plus the
`gradients[i]=1` hack at cnn.c:141-142, commented "This isn't right" — the
two together equal the softmax-CE gradient, SURVEY.md §2.5). Here the loss
is expressed directly as softmax cross-entropy, whose exact gradient w.r.t.
logits is that same `softmax(logits) - onehot`.

Its only training-progress metric is the running squared error
`sum((outputs - onehot)^2)` (Layer_getErrorTotal, cnn.c:275-282), kept here
for log parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jnp.ndarray, onehot: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax-CE over the batch. d/dlogits = (softmax - onehot)/N,
    matching the reference's error seeding divided by batch (the reference
    divides by batch at update time instead: rate/batch_size, cnn.c:469)."""
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.sum(onehot * logz, axis=-1))


def squared_error_total(probs: jnp.ndarray, onehot: jnp.ndarray) -> jnp.ndarray:
    """Reference's etotal metric (cnn.c:275-282): sum of squared residuals."""
    d = probs.astype(jnp.float32) - onehot
    return jnp.sum(d * d) / probs.shape[0]
