"""Losses and training metrics.

The reference never writes a loss function: it seeds the backward pass with
`errors = outputs - onehot` after a softmax forward (cnn.c:284-286 plus the
`gradients[i]=1` hack at cnn.c:141-142, commented "This isn't right" — the
two together equal the softmax-CE gradient, SURVEY.md §2.5). Here the loss
is expressed directly as softmax cross-entropy, whose exact gradient w.r.t.
logits is that same `softmax(logits) - onehot`.

Its only training-progress metric is the running squared error
`sum((outputs - onehot)^2)` (Layer_getErrorTotal, cnn.c:275-282), kept here
for log parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jnp.ndarray, onehot: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax-CE over the batch. d/dlogits = (softmax - onehot)/N,
    matching the reference's error seeding divided by batch (the reference
    divides by batch at update time instead: rate/batch_size, cnn.c:469)."""
    logz = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.sum(onehot * logz, axis=-1))


def squared_error_total(probs: jnp.ndarray, onehot: jnp.ndarray) -> jnp.ndarray:
    """Reference's etotal metric (cnn.c:275-282): sum of squared residuals."""
    d = probs.astype(jnp.float32) - onehot
    return jnp.sum(d * d) / probs.shape[0]


def chunked_ce_mean(feats, head, targets, ce_chunk: int,
                    compute_dtype=None) -> jnp.ndarray:
    """Mean next-token NLL from final-LN features WITHOUT materializing
    the (B, S, V) f32 logits.

    The head matmul runs in S-chunks of `ce_chunk` inside a lax.scan;
    each chunk's logsumexp + target-logit reduce to (B, chunk) scalars
    under jax.checkpoint, so backward recomputes the chunk logits
    instead of saving them — peak extra memory O(B * chunk * V). Dense
    logits at vocab 8k x s 2k x b 8 are 512 MB of HBM; at 32k+ vocab
    they stop fitting at all. Numerics match the dense path: matmul in
    compute dtype with f32 accumulation (preferred_element_type), the
    softmax algebra in f32 (parity-tested, tests/test_lm.py).

    feats: (B, S, d); head: (d, V) master (f32); targets: (B, S) int32.
    Shard-local callers (parallel/sp.py) pass their local S — equal
    shards make the pmean of per-shard means the global mean.
    """
    b, s, d = feats.shape
    if s % ce_chunk:
        raise ValueError(f"ce_chunk {ce_chunk} must divide seq len {s}")
    n = s // ce_chunk
    head = head.astype(compute_dtype) if compute_dtype else head

    def chunk_nll(f_c, t_c):
        logits = jnp.matmul(f_c, head, preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)               # (B, c)
        tgt = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        return jnp.sum(lse - tgt)

    chunk_nll = jax.checkpoint(chunk_nll)
    fs = jnp.moveaxis(feats.reshape(b, n, ce_chunk, d), 1, 0)
    ts = jnp.moveaxis(targets.reshape(b, n, ce_chunk), 1, 0)
    total, _ = jax.lax.scan(
        lambda acc, ft: (acc + chunk_nll(*ft), None),
        jnp.zeros((), jnp.float32), (fs, ts),
    )
    return total / (b * s)
