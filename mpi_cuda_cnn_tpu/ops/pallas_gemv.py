"""Per-channel int8 weight quantization + the decode GEMV kernel.

With the KV cache already int8 under GQA/MQA (auto dtype routing,
PR 9), the WEIGHT stream is the dominant byte mover of a decode tick:
every weight matrix is read once per token at B = slots, T = 1 — pure
GEMV, bandwidth-bound, zero reuse. This module quarters those bytes
with the same absmax contract the cache uses, applied per OUTPUT
channel: w (din, dout) stores as int8 values + one f32 scale per
column, and the scale — constant along the contracted din — multiplies
the OUTPUT after the dot, never entering the MXU contraction (the
int8-KV discipline of models/generate.init_cache, applied to weights).

Quantization is ONE-TIME (`quantize_decode_params` at engine/bench
construction, keyed off --decode-weights-dtype); the decode hot loop
only ever reads the int8 form. `QuantW` is a registered pytree, so
quantized params flow through the jitted decode programs unchanged,
and `qmatmul` is the single dispatch point the shared decode skeleton
(models/generate.token_forward + transformer.project_qkv/apply_block)
calls for every weight matmul: a plain array takes the `@` it always
took, a QuantW takes the fused Pallas GEMV below. One forward
implementation, two storage formats — exactly the cache's design.

Error contract: per-channel absmax bounds each weight's relative error
by 1/254, and the scales are exact f32 multiplies outside the dot, so
logit error is test-bounded the same way the int8 KV cache's is
(tests/test_paged_kernel.py, 5e-2 band vs f32 weights — the discipline
of test_generate's int8-cache pin). MoE expert banks and the embedding
tables are left in f32: experts route through moe_mlp_inference's own
einsums (a separate lever), and tok_emb/pos_emb are gathers, not GEMVs.

The kernel tiles dout (the only axis with reuse to exploit at T=1) and
keeps x resident: grid (dout/TILE,), each step one
(B, din) x (din, TILE) MXU contraction with the int8 tile dequantized
on load and the f32 scale row applied to the output tile. Interpret
mode (non-TPU backends) runs the same kernel body — the tier-1 suite
pins `int8_gemv` == the jnp dequantized form on CPU.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


@dataclasses.dataclass
class QuantW:
    """Per-output-channel int8 weight: values (din, dout) int8, scales
    (1, dout) f32 with w ~= q * s. A registered pytree — jitted decode
    programs carry it like any other param leaf."""

    q: jnp.ndarray
    s: jnp.ndarray

    @property
    def shape(self):
        return self.q.shape


jax.tree_util.register_dataclass(QuantW, data_fields=["q", "s"],
                                 meta_fields=[])


def quantize_weight(w) -> QuantW:
    """Absmax int8 quantization per output channel: w (din, dout) ->
    (int8 values, f32 scales (1, dout)) with w ~= values * scales."""
    wf = jnp.asarray(w, jnp.float32)
    s = jnp.max(jnp.abs(wf), axis=0, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-10)
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return QuantW(q=q, s=s)


def dequantize_weight(w: QuantW) -> jnp.ndarray:
    """The f32 form the GEMV is parity-tested against."""
    return w.q.astype(jnp.float32) * w.s


# The decode-path matmul weights quantize_decode_params converts: every
# per-block GEMV (QKV/out/MLP) plus the head — the byte movers of a
# decode tick. Embeddings are gathers; layernorm params are O(dim).
_BLOCK_WEIGHTS = ("wqkv", "wq", "wkv", "wo", "w1", "w2")


def quantize_decode_params(params: dict, dtype: str) -> dict:
    """One-time serving-weights conversion keyed off
    --decode-weights-dtype: "float32" passes through, "bfloat16" casts
    the f32 leaves (the PERF.md-measured serving cast), "int8" replaces
    the decode GEMV matrices with QuantW (per-channel absmax). The
    returned tree feeds the SAME forward as the f32 one — qmatmul
    dispatches on the leaf type, so there is no second decode path."""
    if dtype == "float32":
        return params
    if dtype == "bfloat16":
        return jax.tree.map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params,
        )
    if dtype != "int8":
        raise ValueError(
            f"decode weights dtype {dtype!r}: want float32, bfloat16, "
            "or int8 (or 'auto' resolved by pick_weights_dtype first)"
        )
    out = dict(params)
    out["head"] = quantize_weight(params["head"])
    blocks = []
    for blk in params["blocks"]:
        nb = dict(blk)
        for name in _BLOCK_WEIGHTS:
            if name in nb:
                nb[name] = quantize_weight(nb[name])
        blocks.append(nb)
    out["blocks"] = blocks
    return out


def _gemv_tile(dout: int) -> int:
    """Largest multiple of 128 dividing dout, capped at 512; a dout the
    lane width doesn't divide runs as one tile (interpret-mode shapes —
    on TPU, model dims are 128-multiples)."""
    if dout % 128:
        return dout
    t = min(512, dout)
    while dout % t:
        t -= 128
    return t


def _gemv_kernel(x_ref, w_ref, s_ref, o_ref):
    o_ref[:] = jax.lax.dot_general(
        x_ref[:], w_ref[:].astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * s_ref[:]


def _run_gemv(n, din, dout, tile, operands):
    """The one pallas_call site — the MCT007 producer declared for this
    module in the lint manifest."""
    return pl.pallas_call(
        _gemv_kernel,
        grid=(dout // tile,),
        in_specs=[
            pl.BlockSpec((n, din), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((din, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((n, tile), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, dout), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(*operands)


def int8_gemv(x: jnp.ndarray, w: QuantW) -> jnp.ndarray:
    """y = (x @ w.q) * w.s: (N, din) f32 x QuantW(din, dout) ->
    (N, dout) f32. The int8 tile converts on load inside the kernel;
    the per-channel scale row multiplies the OUTPUT tile — constant
    along the contracted din, it never enters the MXU contraction (the
    absmax contract; equal to x @ dequant(w) up to one reassociated
    multiply)."""
    n, din = x.shape
    dout = w.q.shape[1]
    tile = _gemv_tile(dout)
    return _run_gemv(n, din, dout, tile,
                     [x.astype(jnp.float32), w.q, w.s])


def qmatmul(x, w):
    """THE decode-weight matmul dispatch: plain arrays keep the `@` the
    forward always used; QuantW routes to the fused int8 GEMV. Accepts
    any leading batch shape (flattened around the kernel)."""
    if not isinstance(w, QuantW):
        return x @ w
    lead = x.shape[:-1]
    y = int8_gemv(x.reshape(-1, x.shape[-1]), w)
    return y.reshape(*lead, w.q.shape[1])
