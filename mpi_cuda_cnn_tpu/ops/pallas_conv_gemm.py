"""Implicit-GEMM (im2col-in-VMEM) Pallas conv — the deep-shape formulation.

The direct kernel (pallas_ops.py `_conv1_kernel`, the TPU twin of
CUDAcnn.cu:167-195) loses to XLA's conv emitter at every measured shape
(PERF.md per-shape table). At the DEEP shapes (Cin >= 64) the mechanism
is lane waste: it issues kh*kw separate MXU contractions with K = Cin,
and Cin = 64 fills half of the MXU's 128 contraction lanes. This module
tries the standard fix the round-4 verdict asked for: build the im2col
patch tile IN VMEM (never in HBM — materialized patches would cost
kh*kw times the input's HBM traffic, which is why the XLA-side im2col
was never the answer) and feed the MXU ONE (BN*OH*OW, kh*kw*Cin)
contraction per tile:

    out = P @ W_flat,  P[:, (ky*kw+kx)*Cin : +Cin] = window(ky, kx)

At Cin=64, K grows 64 -> 576: ~90% lane utilization over the direct
kernel's 50%, and one accumulator pass instead of nine.

The window slices are the same VPU relayouts the direct kernel performs;
the change is purely how the MXU consumes them (concatenated once vs
nine half-filled dots). Stride-1 only — the deep VGG/CIFAR shapes where
the gap lives are all k3/s1/p1; strided convs keep the space-to-batch
direct path (pallas_ops._conv_forward). Backward reuses pallas_ops'
existing kernels (dx transposed-conv, dw accumulator) unchanged.

Measured verdict lives in PERF.md ("Pallas conv/dense kernels" section);
`scripts/bench_conv_shapes.py` emits the three-way comparison rows
(XLA / direct / gemm) unconditionally.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_ops import (
    _conv_bwd,
    _flatten_pixels,
    _interpret,
)


def _conv1_gemm_kernel(x_ref, w_ref, o_ref, *, kh, kw, oh, ow):
    """One batch tile of stride-1 valid conv as ONE MXU contraction.

    x_ref: (BN, Hp, Wp, Cin) VMEM block, Hp >= oh+kh-1, Wp >= ow+kw-1.
    w_ref: (kh*kw*Cin, Cout) — the kernel pre-flattened in patch order.
    o_ref: (BN, OH, OW, Cout).

    All kh*kw window slices are static (small k: the VMEM budget picker
    accounts for every live slice), concatenated on the lane dim into
    the patch tile P, then a single dot. The concat is a lane-dim
    relayout — the same per-offset copies the direct kernel performs —
    but the contraction runs once at K = kh*kw*Cin instead of kh*kw
    times at K = Cin.
    """
    bn = x_ref.shape[0]
    cin = x_ref.shape[3]
    m = bn * oh * ow
    slices = [
        x_ref[:, ky : ky + oh, kx : kx + ow, :]
        for ky in range(kh)
        for kx in range(kw)
    ]
    if x_ref.dtype == jnp.float32:
        # Concatenate the window slices as 4-D values FIRST, then one
        # pixel flatten — measurably faster (this ordering is what puts
        # the deep f32 shapes AT or past XLA, PERF.md round-5 table).
        p4 = jnp.concatenate(slices, axis=-1)  # (BN, OH, OW, kh*kw*Cin)
        p = p4.reshape(m, kh * kw * cin)
    else:
        # Packed dtypes: Mosaic rejects the 4-D lane concat ("offset
        # mismatch on non-concat dimension"), so flatten each slice
        # (f32 round-trip) and concat in 2-D.
        p = jnp.concatenate(
            [_flatten_pixels(s, m, cin) for s in slices], axis=-1
        )                                               # (M, kh*kw*Cin)
    o_ref[:] = (
        jnp.dot(p, w_ref[:], preferred_element_type=jnp.float32)
        .reshape(o_ref.shape)
        .astype(o_ref.dtype)
    )


def _pick_gemm_batch_tile(
    n, hp, wp, cin, oh, ow, cout, kh, kw, itemsize, budget=10 * 2**20
) -> int:
    """Largest batch tile whose working set fits VMEM: the x block, all
    kh*kw live window slices PLUS the concatenated patch tile (both f32
    — _flatten_pixels round-trips packed dtypes), the f32 dot result,
    and the out block. Lane(128)/sublane padding counted like
    pallas_ops._pick_batch_tile."""
    lane = lambda c: -(-c // 128) * 128
    s_mult = 8 * max(4 // itemsize, 1)
    sub = lambda s: -(-s // s_mult) * s_mult
    k_flat = kh * kw * cin
    per_sample = (
        hp * sub(wp) * lane(cin) * itemsize       # x block
        + kh * kw * oh * ow * lane(cin) * 4       # live window slices (f32)
        + oh * ow * lane(k_flat) * 4              # patch tile (f32)
        + oh * ow * lane(cout) * 4                # f32 dot result
        + oh * sub(ow) * lane(cout) * itemsize    # out block
    )
    bn = max(1, min(n, budget // max(per_sample, 1)))
    while n % bn:
        bn -= 1
    return bn


def _conv1_gemm(x: jnp.ndarray, w: jnp.ndarray, oh: int, ow: int):
    """Stride-1 valid conv via the implicit-GEMM kernel; x pre-padded."""
    n, hp, wp, cin = x.shape
    kh, kw, _, cout = w.shape
    bn = _pick_gemm_batch_tile(
        n, hp, wp, cin, oh, ow, cout, kh, kw, x.dtype.itemsize
    )
    w_flat = w.reshape(kh * kw * cin, cout)
    kernel = functools.partial(_conv1_gemm_kernel, kh=kh, kw=kw, oh=oh, ow=ow)
    return pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec(
                (bn, hp, wp, cin), lambda i: (i, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (kh * kw * cin, cout), lambda i: (0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (bn, oh, ow, cout), lambda i: (i, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((n, oh, ow, cout), x.dtype),
        interpret=_interpret(),
    )(x, w_flat)


def _conv_gemm_forward(x, w, stride: int, padding: int):
    if stride != 1:
        raise ValueError(
            f"conv2d_pallas_gemm is the stride-1 formulation (got stride "
            f"{stride}); strided convs use conv2d_pallas's space-to-batch "
            "direct path"
        )
    n, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    oh = h + 2 * padding - kh + 1
    ow = wd + 2 * padding - kw + 1
    xp = jnp.pad(x, ((0, 0), (padding, padding), (padding, padding), (0, 0)))
    return _conv1_gemm(xp[:, : oh + kh - 1, : ow + kw - 1, :], w, oh, ow)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv2d_pallas_gemm(x, w, stride: int = 1, padding: int = 0):
    """Implicit-GEMM conv forward (stride-1): same contract as
    conv2d_pallas — x: (N,H,W,Cin), w: (kh,kw,Cin,Cout) — different MXU
    feeding. Backward shares pallas_ops' kernels (the formulation choice
    is forward-only)."""
    return _conv_gemm_forward(x, w, stride, padding)


def _gemm_fwd(x, w, stride, padding):
    return _conv_gemm_forward(x, w, stride, padding), (x, w)


conv2d_pallas_gemm.defvjp(_gemm_fwd, _conv_bwd)
