"""Analyzer core: Finding, the Rule protocol, the shared visitor, and
per-line suppressions.

One AST parse and ONE tree walk per file, however many rules run: each
rule declares the node types it wants (`node_types`) and the walker
dispatches every matching node to every subscribed rule. Rules are
small classes — the Engler-style pattern is "state the invariant, visit
the two node shapes that can break it" — and findings carry exact
file:line:col so a CI annotation lands on the offending token.

Suppressions: `# mctpu: disable=MCT001` (comma-separate for several,
`disable=all` for every rule) on the finding's line, or on a
standalone comment line directly above it. A suppression is a visible,
reviewable exception at the site; the committed baseline
(ci/lint_baseline.json) is for pre-existing debt only and ships empty.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

from .manifest import Manifest

# Directories never scanned: the C driver tree, caches, VCS internals.
SKIP_DIRS = {".git", "__pycache__", ".github", "native", ".pytest_cache"}

# Capture ONLY comma-separated rule-id tokens: trailing prose on the
# same pragma ("# mctpu: disable=MCT002 injectable default") must not
# be swallowed into the token, or the visibly-present pragma silently
# suppresses nothing.
_SUPPRESS_RE = re.compile(
    r"#\s*mctpu:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)


class LintError(Exception):
    """Configuration/environment error (bad manifest, unparsable file):
    exit 2, distinct from findings (exit 1)."""


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at an exact source location. `path` is
    repo-root-relative POSIX (the baseline's stable key — absolute
    paths would break the committed file across checkouts)."""

    rule: str
    path: str
    line: int
    col: int
    msg: str

    def key(self) -> tuple[str, str, int]:
        """Baseline identity: rule + file + line. Column is excluded so
        a same-line reformat does not resurrect a baselined finding."""
        return (self.rule, self.path, self.line)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.msg}"


class FileContext:
    """Everything a rule may consult about the file under analysis."""

    def __init__(self, rel: str, source: str, tree: ast.Module,
                 manifest: Manifest):
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.manifest = manifest
        self.findings: list[Finding] = []
        self._suppressed = _suppression_map(self.lines)
        self._bindings: dict[str, str] | None = None

    @property
    def is_test(self) -> bool:
        parts = self.rel.split("/")
        return "tests" in parts or Path(self.rel).name.startswith("test_")

    @property
    def import_bindings(self) -> dict[str, str]:
        """name -> canonical dotted origin for every import in the file
        (`import time as t` -> {"t": "time"}, `from datetime import
        datetime as dt` -> {"dt": "datetime.datetime"}). Computed once
        per file and shared by every rule that needs to resolve an
        aliased or from-imported spelling back to its module — so
        `t.monotonic()` and `dt.now()` cannot evade a module-keyed ban,
        and `from jax import random` is distinguishable from the stdlib
        `random`. Relative imports are first-party and excluded (rules
        that care about those resolve them path-wise, see MCT001)."""
        if self._bindings is None:
            b: dict[str, str] = {}
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        b[a.asname or a.name.split(".", 1)[0]] = (
                            a.name if a.asname else a.name.split(".", 1)[0])
                elif isinstance(node, ast.ImportFrom) and node.level == 0 \
                        and node.module:
                    for a in node.names:
                        if a.name != "*":
                            b[a.asname or a.name] = \
                                f"{node.module}.{a.name}"
            self._bindings = b
        return self._bindings

    def canonical(self, dotted: str) -> str:
        """Rewrite a dotted chain's head through import_bindings:
        "t.monotonic" -> "time.monotonic", "dt.now" ->
        "datetime.datetime.now". Unbound heads pass through."""
        head, _, rest = dotted.partition(".")
        origin = self.import_bindings.get(head)
        if origin is None:
            return dotted
        return f"{origin}.{rest}" if rest else origin

    def report(self, rule: str, node: ast.AST, msg: str) -> None:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        if not self.suppressed(rule, line):
            self.findings.append(Finding(rule, self.rel, line, col, msg))

    def suppressed(self, rule: str, line: int) -> bool:
        active = self._suppressed.get(line, frozenset())
        return rule in active or "all" in active


def _suppression_map(lines: list[str]) -> dict[int, frozenset[str]]:
    """line (1-based) -> rule ids suppressed there. A comment-only line
    carrying a disable pragma suppresses the next non-blank line too
    (same-line pragmas on 100-char lines rarely fit)."""
    out: dict[int, set[str]] = {}
    pending: set[str] | None = None
    for i, text in enumerate(lines, 1):
        m = _SUPPRESS_RE.search(text)
        stripped = text.strip()
        if m:
            rules = {r.strip() for r in m.group(1).split(",")}
            out.setdefault(i, set()).update(rules)
            if stripped.startswith("#"):
                pending = rules  # standalone pragma: covers the next line
                continue
        elif pending is not None and stripped and not stripped.startswith("#"):
            out.setdefault(i, set()).update(pending)
        if stripped:
            pending = None
    return {k: frozenset(v) for k, v in out.items()}


class Rule:
    """Base class: subclasses set `rule_id`, `title`, `node_types`, and
    implement `visit`. `begin_file` returning False skips the file
    entirely (scope decisions — manifests, test exclusions — live
    there, not in every visit)."""

    rule_id: str = "MCT000"
    title: str = ""
    node_types: tuple[type, ...] = ()

    def begin_file(self, ctx: FileContext) -> bool:
        return True

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        raise NotImplementedError

    def report(self, ctx: FileContext, node: ast.AST, msg: str) -> None:
        ctx.report(self.rule_id, node, msg)


def collect_files(root: Path, paths: list[str]) -> list[Path]:
    """Resolve PATHS (files or directories, relative to `root`) into the
    sorted .py file list to scan. Unknown paths are a config error —
    a typo'd path silently scanning nothing would green-light CI."""
    files: set[Path] = set()
    for p in paths:
        target = (root / p) if not Path(p).is_absolute() else Path(p)
        # Findings and manifest/baseline entries key on root-relative
        # paths, so a target outside the root has no stable identity —
        # a config error (exit 2), not a traceback.
        if not target.resolve().is_relative_to(root.resolve()):
            raise LintError(
                f"lint path {p} is outside the repo root {root} — "
                "findings are keyed root-relative; run from the repo "
                "or pass --manifest from the target checkout"
            )
        target = target.resolve()
        if target.is_file():
            files.add(target)
        elif target.is_dir():
            for f in sorted(target.rglob("*.py")):
                if not SKIP_DIRS.intersection(f.relative_to(root).parts):
                    files.add(f)
        else:
            raise LintError(f"lint path does not exist: {p}")
    return sorted(files)


def lint_file(path: Path, root: Path, rules: list[Rule],
              manifest: Manifest) -> list[Finding]:
    rel = path.relative_to(root).as_posix()
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        raise LintError(f"{rel}:{e.lineno}: cannot parse: {e.msg}") from e
    ctx = FileContext(rel, source, tree, manifest)
    active = [r for r in rules if r.begin_file(ctx)]
    if not active:
        return []
    # ONE walk, whatever the rule count: dispatch by node type.
    by_type: dict[type, list[Rule]] = {}
    for r in active:
        for t in r.node_types:
            by_type.setdefault(t, []).append(r)
    for node in ast.walk(tree):
        for r in by_type.get(type(node), ()):
            r.visit(node, ctx)
    return sorted(ctx.findings, key=lambda f: (f.line, f.col, f.rule))


def lint_paths(paths: list[str], *, root: Path, manifest: Manifest,
               rules: list[Rule] | None = None) -> list[Finding]:
    """Run `rules` (default: every shipped rule) over `paths`; findings
    come back sorted by (path, line, col, rule). The programmatic
    entry point — tests drive it with synthetic manifests."""
    if rules is None:
        from . import all_rules

        rules = all_rules()
    # One resolve up front: collect_files resolves each target, so the
    # root must be resolved too or relative_to mismatches on symlinked
    # roots (macOS /tmp, bind mounts).
    root = Path(root).resolve()
    findings: list[Finding] = []
    for f in collect_files(root, paths):
        findings.extend(lint_file(f, root, rules, manifest))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def dotted_name(node: ast.AST) -> str | None:
    """Render a Name/Attribute chain ("self.compute.prefill_chunk");
    None for anything dynamic (subscripts, calls)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
