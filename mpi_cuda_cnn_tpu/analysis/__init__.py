"""analysis — the framework-invariant static analyzer behind `mctpu lint`.

Nine PRs of review-hardening accumulated a set of contracts that lived
only as prose in CHANGES.md — "the scheduler/router/slo/alerts layer is
jax-free", "wall-clock goes through an injectable clock", "donation only
via donate_jit", "every JSONL record uses a registered schema family",
"every fault hook site is in faults.SITES" — and each was violated at
least once before a reviewer caught it by hand. In the spirit of
deviant-behavior inference (Engler et al., SOSP 2001: the codebase's own
majority usage IS the specification) and always-on analyzer platforms
(Sadowski et al., Tricorder, ICSE 2015: checks that run on every change,
with precise findings and in-code suppressions, are the ones that stick),
this package encodes those contracts as AST rules that run on every PR.

Layout:
- `core`        — Finding, the Rule protocol, the shared single-pass
                  visitor, per-line `# mctpu: disable=MCTxxx` suppressions.
- `manifest`    — the checked-in contract manifest (ci/lint_manifest.json):
                  which modules are declared jax-free, the allowlisted
                  clock/donation modules, the hot-loop sites.
- `rules_purity`     — MCT001 jax-purity of manifested modules.
- `rules_discipline` — MCT002 clock, MCT003 donation, MCT004 RNG.
- `rules_crosscheck` — MCT005 schema families, MCT006 fault sites
                  (semantic: the live registries are imported, not
                  regexed, so the rule and the runtime cannot drift).
- `rules_hotloop`    — MCT007 host-sync-in-hot-loop.
- `baseline`    — the committed zero-entry baseline (ci/lint_baseline.json)
                  that makes CI fail on any NEW finding.
- `cli`         — `mctpu lint [PATHS] [--rule MCTxxx] [--format json]`.

This package is itself declared jax-free in the manifest: `mctpu lint`
must run on a machine with no accelerator stack warmed up.
"""

from __future__ import annotations

from .baseline import load_baseline, write_baseline
from .core import Finding, LintError, Rule, lint_paths
from .manifest import Manifest, find_root, load_manifest
from .rules_crosscheck import FaultSiteRule, SchemaFamilyRule
from .rules_discipline import ClockRule, DonationRule, RngRule
from .rules_hotloop import HostSyncRule
from .rules_purity import JaxPurityRule

__all__ = [
    "ALL_RULES",
    "Finding",
    "LintError",
    "Manifest",
    "Rule",
    "all_rules",
    "find_root",
    "lint_paths",
    "load_baseline",
    "load_manifest",
    "write_baseline",
]

# The shipped rule set, in rule-id order. A rule class is instantiated
# per lint run (rules hold no cross-run state).
ALL_RULES = (
    JaxPurityRule,      # MCT001
    ClockRule,          # MCT002
    DonationRule,       # MCT003
    RngRule,            # MCT004
    SchemaFamilyRule,   # MCT005
    FaultSiteRule,      # MCT006
    HostSyncRule,       # MCT007
)


def all_rules() -> list[Rule]:
    """Fresh instances of every shipped rule."""
    return [cls() for cls in ALL_RULES]
