"""`mctpu lint [PATHS] [--rule MCTxxx] [--format json] [--baseline F]`.

Exit codes follow the repo's gate convention (obs.regress/health):
0 = clean, 1 = findings, 2 = configuration error. `--format json`
prints one machine-readable object (CI uploads it as an artifact);
text mode prints one `path:line:col: MCTxxx message` per finding plus
a one-line summary on stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import ALL_RULES, all_rules
from .baseline import apply_baseline, load_baseline, write_baseline
from .core import LintError, lint_paths
from .manifest import MANIFEST_REL, find_root, load_manifest

KNOWN_RULES = tuple(cls.rule_id for cls in ALL_RULES)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mctpu lint",
        description="Framework-invariant static analyzer: jax-purity, "
                    "clock/RNG/donation discipline, schema and "
                    "fault-site cross-checks (rules MCT001-MCT007).",
    )
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: the "
                        "manifest's checked-in scope)")
    p.add_argument("--rule", action="append", metavar="MCTxxx",
                   help="run only this rule (repeatable)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", metavar="FILE",
                   help="suppress findings recorded in this baseline "
                        "(ci/lint_baseline.json)")
    p.add_argument("--write-baseline", metavar="FILE",
                   help="write current findings as the new baseline "
                        "and exit 0")
    p.add_argument("--manifest", metavar="FILE",
                   help=f"contract manifest (default: <root>/{MANIFEST_REL})")
    return p


def lint_main(argv: list[str]) -> int:
    args = build_parser().parse_args(argv)
    try:
        root = (find_root(Path(args.manifest).resolve().parent)
                if args.manifest else find_root())
        manifest = load_manifest(args.manifest or root / MANIFEST_REL)
        rules = all_rules()
        if args.rule:
            wanted = set(args.rule)
            unknown = sorted(wanted - set(KNOWN_RULES))
            if unknown:
                raise LintError(
                    f"unknown rule(s) {', '.join(unknown)} "
                    f"(known: {', '.join(KNOWN_RULES)})"
                )
            rules = [r for r in rules if r.rule_id in wanted]
        paths = args.paths or list(manifest.paths)
        findings = lint_paths(paths, root=root, manifest=manifest,
                              rules=rules)
        if args.write_baseline:
            write_baseline(findings, args.write_baseline)
            print(f"wrote {len(findings)} finding(s) to "
                  f"{args.write_baseline}", file=sys.stderr)
            return 0
        if args.baseline:
            findings = apply_baseline(findings, load_baseline(args.baseline))
    except LintError as e:
        print(f"mctpu lint: error: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "rules": [r.rule_id for r in rules],
            "paths": paths,
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "col": f.col, "msg": f.msg}
                for f in findings
            ],
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        print(
            f"mctpu lint: {len(findings)} finding(s) "
            f"[{', '.join(r.rule_id for r in rules)}]",
            file=sys.stderr,
        )
    return 1 if findings else 0
