"""MCT007 — host sync on a device value inside a serving hot loop.

The bug class PR 7 fixed by hand: `int()` / `float()` / `.item()` /
`np.asarray()` on a value still on the device forces a blocking
device->host transfer. Once per batched tick that is the sanctioned
sync point; once per prefill CHUNK it serializes the whole pipeline —
the engine used to int() every chunk's next-token and pay a round trip
per 32 prompt tokens until run_prefill_chunk was changed to return the
device array and convert only on the completing chunk.

Statically, "is this value on the device" needs dataflow, so the rule
is scoped by the manifest: hot_loops declares, per file, the function
bodies that are serving hot loops and the dotted call targets whose
results are device values (the jitted programs `self._tick` /
`self._prefill` / `self._copy`, and the documented device-returning
helper `self.run_prefill_chunk`). Inside a hot function the rule walks
statements IN SOURCE ORDER, tainting names assigned from producer
calls (tuple unpacking taints every target — which element holds the
device array is not statically knowable) and clearing taint on
reassignment from clean values; a conversion call whose argument
involves a tainted name (or a producer call directly) is a finding.

The two sanctioned syncs in the shipped tree — the batched decode
tick's one-per-tick np.asarray and the completing prefill chunk's
int() — carry commented suppressions at the site: the rule's job is to
make the NEXT per-chunk sync impossible to add silently, not to
relitigate the two the design documents.
"""

from __future__ import annotations

import ast

from .core import FileContext, Rule, dotted_name

_CONVERTERS_NAME = {"int", "float"}
_CONVERTERS_DOTTED = {"np.asarray", "np.array", "numpy.asarray",
                      "numpy.array", "jax.device_get"}


def _stmt_exprs(stmt: ast.stmt):
    """The statement's OWN expressions (test/value/iter/...), excluding
    nested statement blocks — those are walked recursively in source
    order so assignments update taint at the right point."""
    for _, value in ast.iter_fields(stmt):
        if isinstance(value, ast.expr):
            yield value
        elif isinstance(value, list):
            for v in value:
                if isinstance(v, ast.expr):
                    yield v
                elif isinstance(v, ast.withitem):
                    yield v.context_expr


class HostSyncRule(Rule):
    rule_id = "MCT007"
    title = "host sync on a device value inside a declared hot loop"
    node_types = (ast.FunctionDef, ast.AsyncFunctionDef)

    def begin_file(self, ctx: FileContext) -> bool:
        self._spec = ctx.manifest.hot_loops.get(ctx.rel)
        return self._spec is not None

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        if node.name not in self._spec.functions:
            return
        walker = _TaintWalker(self, ctx, self._spec.producers)
        walker.run(node.body)


class _TaintWalker:
    """Source-order statement walk with a name-level taint set.

    Deliberately linear (no loop fixed point): taint introduced late in
    a loop body does not flow back to the top. The hot loops this rule
    guards assign their device results and convert them within one
    iteration's straight-line code, and a linear walk keeps findings
    explainable — the producer assignment is always textually above the
    flagged conversion.
    """

    def __init__(self, rule: Rule, ctx: FileContext,
                 producers: frozenset[str]):
        self.rule = rule
        self.ctx = ctx
        self.producers = producers
        self.tainted: set[str] = set()

    # -- taint queries ----------------------------------------------------

    def _is_producer_call(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and dotted_name(node.func) in self.producers)

    def _expr_tainted(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                return True
            if self._is_producer_call(sub):
                return True
        return False

    # -- walk -------------------------------------------------------------

    def run(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        # Flag conversions BEFORE updating taint: `x = int(x)` on a
        # tainted x is still a sync.
        for expr in _stmt_exprs(stmt):
            self._scan_conversions(expr)
        if isinstance(stmt, ast.Assign):
            tainted = self._expr_tainted(stmt.value)
            for target in stmt.targets:
                self._assign(target, tainted)
        elif isinstance(stmt, ast.AugAssign):
            if self._expr_tainted(stmt.value) and \
                    isinstance(stmt.target, ast.Name):
                self.tainted.add(stmt.target.id)
        # Recurse into compound statements in source order; nested
        # function/class defs are separate scopes the manifest would
        # name explicitly.
        for body_attr in ("body", "orelse", "finalbody"):
            for sub in getattr(stmt, body_attr, ()):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                    continue
                if isinstance(sub, ast.stmt):
                    self._stmt(sub)
        for handler in getattr(stmt, "handlers", ()):
            for sub in handler.body:
                self._stmt(sub)

    def _assign(self, target: ast.expr, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            # Which element carries the device array is not statically
            # knowable: taint (or clear) them all.
            for elt in target.elts:
                self._assign(elt, tainted)

    def _scan_conversions(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # int(x) / float(x)
            if isinstance(func, ast.Name) and func.id in _CONVERTERS_NAME:
                if node.args and self._expr_tainted(node.args[0]):
                    self._flag(node, f"{func.id}()")
            # np.asarray(x) / jax.device_get(x)
            elif (dn := dotted_name(func)) in _CONVERTERS_DOTTED:
                if node.args and self._expr_tainted(node.args[0]):
                    self._flag(node, f"{dn}()")
            # x.item()
            elif isinstance(func, ast.Attribute) and func.attr == "item" \
                    and not node.args and self._expr_tainted(func.value):
                self._flag(node, ".item()")

    def _flag(self, node: ast.Call, what: str) -> None:
        self.rule.report(
            self.ctx, node,
            f"{what} on a device value inside a declared hot loop forces "
            "a blocking device->host sync — keep it a device array "
            "(convert once per tick / on the completing chunk, with a "
            "commented suppression at the sanctioned site)",
        )
