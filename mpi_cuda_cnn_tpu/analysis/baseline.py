"""Finding baseline (ci/lint_baseline.json): fail on NEW findings only.

Tricorder's adoption lesson: an analyzer bolted onto a living codebase
must not force a flag day — pre-existing findings go into a committed
baseline and CI reds only on findings the current change introduced.
This repo's baseline ships EMPTY (the tree was brought fully clean in
the same PR that added the analyzer, with genuine exceptions suppressed
at the site, where reviewers see them); the mechanism exists so a future
rule with real pre-existing debt can land enforcing-for-new-code first,
and so the round-trip is testable.

Matching is (rule, path, line): stable across reformats of other lines,
intentionally brittle against edits near the baselined site — touching
that code is exactly when the finding should resurface for a decision.
"""

from __future__ import annotations

import json
from pathlib import Path

from .core import Finding, LintError

BASELINE_VERSION = 1


def load_baseline(path: str | Path) -> set[tuple[str, str, int]]:
    p = Path(path)
    if not p.is_file():
        raise LintError(f"baseline not found: {p}")
    try:
        raw = json.loads(p.read_text())
    except json.JSONDecodeError as e:
        raise LintError(f"{p}: bad JSON: {e}") from e
    if raw.get("version") != BASELINE_VERSION:
        raise LintError(
            f"{p}: baseline version {raw.get('version')!r} != "
            f"{BASELINE_VERSION}"
        )
    entries = raw.get("findings")
    if not isinstance(entries, list):
        raise LintError(f"{p}: 'findings' must be a list")
    out: set[tuple[str, str, int]] = set()
    for e in entries:
        try:
            out.add((e["rule"], e["path"], int(e["line"])))
        except (TypeError, KeyError) as exc:
            raise LintError(
                f"{p}: baseline entry needs rule/path/line: {e!r}"
            ) from exc
    return out


def apply_baseline(findings: list[Finding],
                   known: set[tuple[str, str, int]]) -> list[Finding]:
    """Drop findings present in the baseline. Unmatched baseline
    entries are fine — fixed debt just leaves a stale entry that the
    next `--write-baseline` refresh removes."""
    return [f for f in findings if f.key() not in known]


def write_baseline(findings: list[Finding], path: str | Path) -> None:
    """Write the CURRENT findings as the new baseline (tmp+rename — a
    crashed write must not leave CI gating on half a file)."""
    p = Path(path)
    payload = {
        "_doc": "mctpu lint baseline: findings CI tolerates. Keep this "
                "empty — new findings are fixed or suppressed at the "
                "site (# mctpu: disable=MCTxxx with a reason); baseline "
                "entries are for landing a new rule over pre-existing "
                "debt only. Refresh: mctpu lint --write-baseline "
                "ci/lint_baseline.json",
        "version": BASELINE_VERSION,
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line, "msg": f.msg}
            for f in findings
        ],
    }
    tmp = p.with_suffix(p.suffix + ".tmp")
    tmp.write_text(json.dumps(payload, indent=2) + "\n")
    tmp.replace(p)
