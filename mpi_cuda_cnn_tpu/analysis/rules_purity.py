"""MCT001 — jax-purity of modules declared jax-free in the manifest.

The scheduler/router/slo/alerts/metrics/timeline/regress/faults/schema
layer is the framework's POLICY half: it must run in offline tools
(`mctpu report/trace/compare/health`), in the fleet's 10^5-request sim
storms, and in bootstrap scripts, without importing jax — an accidental
jax import turns a millisecond policy test into a device-init, and a
traced op inside a policy decision breaks the FakeClock bitwise
determinism every serving proof rests on.

Two violation shapes:
- importing jax/jaxlib (module level OR lazily inside a function — a
  lazy import is still a jax dependency the first time the branch runs;
  the two deliberate lazy sites in faults.py carry commented
  suppressions, which is the point: exceptions are visible at the site);
- directly importing a first-party module that is NOT itself declared
  jax-free — the one-level closure check that caught
  serve/scheduler.py's lazy `obs.report` import (report -> cost -> jax)
  hiding inside the fleet sim path.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .core import FileContext, Rule

_JAX_ROOTS = ("jax", "jaxlib")


def _is_jax(module: str | None) -> bool:
    if not module:
        return False
    top = module.split(".", 1)[0]
    return top in _JAX_ROOTS


class JaxPurityRule(Rule):
    rule_id = "MCT001"
    title = "jax-free module imports jax or a non-jax-free first-party module"
    node_types = (ast.Import, ast.ImportFrom)

    def begin_file(self, ctx: FileContext) -> bool:
        return ctx.rel in ctx.manifest.jax_free

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                if _is_jax(alias.name):
                    self.report(ctx, node,
                                f"module is declared jax-free "
                                f"(ci/lint_manifest.json) but imports "
                                f"{alias.name!r}")
                elif alias.name.split(".", 1)[0] == \
                        ctx.manifest.first_party_root:
                    self._check_first_party(
                        node, ctx, alias.name.split("."), level=0)
        elif isinstance(node, ast.ImportFrom):
            if _is_jax(node.module):
                self.report(ctx, node,
                            f"module is declared jax-free but imports "
                            f"from {node.module!r}")
            elif node.level > 0 or (
                    node.module or "").split(".", 1)[0] == \
                    ctx.manifest.first_party_root:
                parts = (node.module or "").split(".") if node.module else []
                self._check_first_party(node, ctx, parts, level=node.level)

    def _check_first_party(self, node: ast.AST, ctx: FileContext,
                           parts: list[str], *, level: int) -> None:
        target = _resolve(ctx.rel, parts, level)
        if target is None or target in ctx.manifest.jax_free:
            return
        self.report(
            ctx, node,
            f"jax-free module imports first-party {target!r}, which is "
            "not declared jax-free — it may pull jax transitively "
            "(declare it in ci/lint_manifest.json once it is, or move "
            "the needed helper into a jax-free module)",
        )


def _resolve(rel: str, parts: list[str], level: int) -> str | None:
    """Map an import in file `rel` to the repo-relative .py path of the
    imported module. The manifest lists concrete module files, so the
    .py form is the membership key; a PACKAGE import (`from . import
    obs`, which executes an __init__ chain the jax-free contract can
    never hold for) resolves to a path not in the manifest and is
    reported as a violation — which it is."""
    if level == 0:
        base: list[str] = []
        # Absolute: parts already start at the first-party root, which
        # is a directory at the repo root.
    else:
        parent = Path(rel).parent
        base = [] if parent == Path(".") else list(parent.parts)
        for _ in range(level - 1):
            if not base:
                return None
            base.pop()
    full = [*base, *parts]
    if not full:
        return None
    if not parts:
        # `from . import x`: the import target is the package __init__
        # (the submodules bind as attributes after their own import —
        # a jax-free package like analysis/ declares its __init__).
        return "/".join(full) + "/__init__.py"
    return "/".join(full) + ".py"
