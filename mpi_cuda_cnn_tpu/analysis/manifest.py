"""The checked-in contract manifest the rules read (ci/lint_manifest.json).

The analyzer encodes REPO contracts, not generic style, and a contract
needs a declaration site: which modules claim jax-freedom (MCT001),
which single module may read the wall clock (MCT002) or spell donation
(MCT003), and which function bodies are serving hot loops with which
device-value producers (MCT007). Keeping those declarations in one
committed JSON file — instead of constants inside each rule — means a
reviewer sees scope changes ("engine.py is no longer a hot loop") as a
diff to the manifest, and tests can hand rules a synthetic manifest to
point them at fixture files.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

MANIFEST_REL = "ci/lint_manifest.json"


@dataclasses.dataclass(frozen=True)
class HotLoop:
    """One file's hot-loop declaration: `functions` are the def names
    whose bodies are scanned, `producers` the dotted call targets whose
    results are device values (jitted programs and the documented
    device-array-returning helpers)."""

    functions: frozenset[str]
    producers: frozenset[str]


@dataclasses.dataclass(frozen=True)
class Manifest:
    """Rule configuration. All paths are repo-root-relative POSIX."""

    # Modules declared jax-free: no jax/jaxlib import anywhere in the
    # module, and no direct first-party import of a module outside this
    # set (it may pull jax transitively). Scope note: the package
    # __init__ chain is deliberately NOT part of the contract — it
    # imports the jax-heavy subsystems by design; jax-freedom here means
    # the module's own code adds no jax dependency (offline consumers
    # load these files directly, e.g. scripts/get_mnist.py's
    # by-file-path bootstrap of utils/retry.py).
    jax_free: frozenset[str] = frozenset()
    # The one module allowed to read the wall clock (MCT002).
    clock_modules: frozenset[str] = frozenset()
    # The one module allowed to spell donate_argnums (MCT003).
    donation_module: str = "mpi_cuda_cnn_tpu/utils/donation.py"
    # file -> hot-loop declaration (MCT007).
    hot_loops: dict[str, HotLoop] = dataclasses.field(default_factory=dict)
    # Default scan scope for `mctpu lint` with no PATHS.
    paths: tuple[str, ...] = ("mpi_cuda_cnn_tpu", "scripts", "bench.py")
    # Import prefix that counts as first-party for MCT001.
    first_party_root: str = "mpi_cuda_cnn_tpu"


def load_manifest(path: str | Path) -> Manifest:
    from .core import LintError  # local: core imports Manifest

    p = Path(path)
    if not p.is_file():
        raise LintError(
            f"lint manifest not found: {p} — the analyzer's contracts "
            "(jax-free modules, clock/donation allowlists, hot loops) "
            "live there; pass --manifest or run from the repo root"
        )
    try:
        raw = json.loads(p.read_text())
    except json.JSONDecodeError as e:
        raise LintError(f"{p}: bad JSON: {e}") from e
    known = {"_doc", "jax_free", "clock_modules", "donation_module",
             "hot_loops", "paths", "first_party_root"}
    unknown = sorted(set(raw) - known)
    if unknown:
        # A typo'd key would silently relax the contract it misspells.
        raise LintError(f"{p}: unknown manifest keys {unknown}")
    hot = {}
    for rel, spec in raw.get("hot_loops", {}).items():
        hot[rel] = HotLoop(functions=frozenset(spec.get("functions", ())),
                           producers=frozenset(spec.get("producers", ())))
    return Manifest(
        jax_free=frozenset(raw.get("jax_free", ())),
        clock_modules=frozenset(raw.get("clock_modules", ())),
        donation_module=raw.get(
            "donation_module", "mpi_cuda_cnn_tpu/utils/donation.py"),
        hot_loops=hot,
        paths=tuple(raw.get("paths",
                            ("mpi_cuda_cnn_tpu", "scripts", "bench.py"))),
        first_party_root=raw.get("first_party_root", "mpi_cuda_cnn_tpu"),
    )


def find_root(start: str | Path | None = None) -> Path:
    """Walk up from `start` (default: cwd) to the directory holding
    pyproject.toml — the repo root every manifest/baseline path is
    relative to."""
    from .core import LintError

    p = Path(start or Path.cwd()).resolve()
    if p.is_file():
        p = p.parent
    for candidate in (p, *p.parents):
        if (candidate / "pyproject.toml").is_file():
            return candidate
    raise LintError(
        f"no pyproject.toml above {p} — cannot locate the repo root "
        "(run from inside the repo or pass explicit paths)"
    )
