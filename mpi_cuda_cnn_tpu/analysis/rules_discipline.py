"""MCT002/MCT003/MCT004 — clock, donation, and RNG discipline.

Three rules with one shape: a capability the framework routes through
exactly one sanctioned spelling, and a banned raw form everywhere else.

MCT002 (clock): every serving/fleet/elasticity proof in this repo is
bitwise-deterministic because wall-clock only ever enters through an
injectable `clock` parameter (FakeClock substitutes it in tests). A raw
`time.time()` / `time.monotonic()` / `datetime.now()` read anywhere
else is a nondeterminism leak that FakeClock cannot reach. The one
sanctioned home for real wall-clock reads is the manifest's
clock_modules (utils/clock.py). `time.perf_counter` is deliberately NOT
banned: it is the injectable-clock *default value* convention
("`clock` has the time.perf_counter call shape") — the discipline is
about call sites, and a default argument is the injection point itself.

MCT003 (donation): buffer donation is spelled ONCE, in
utils/donation.donate_jit, and proven from the compiled HLO's alias
table (obs.cost.assert_donation). A raw `donate_argnums=` at a call
site reintroduces exactly the per-site drift PR 2 removed — and
donation silently degrades to a copy on a shape mismatch, so a drifted
site is invisible until the HBM bill arrives.

MCT004 (RNG): every random draw threads a seeded generator
(np.random.default_rng(seed) / jax PRNGKey); the global-state
conveniences (np.random.rand, random.random, np.random.seed) make runs
irreproducible and break the elastic "global batch is a pure function
of (seed, step)" contract. Tests are exempt (they own their seeds);
injectable jitter defaults (faults.supervise, utils/retry) carry
commented suppressions — visible exceptions, not silent ones.
"""

from __future__ import annotations

import ast

from .core import FileContext, Rule, dotted_name

# Canonical dotted names whose evaluation reads the wall clock. Call
# sites are matched AFTER resolving import aliases through
# ctx.canonical (`import time as t; t.monotonic()` and
# `from datetime import datetime as dt; dt.now()` both resolve), and
# `from time import monotonic`-style imports are flagged at the import
# itself — a from-import is the evasion, not its later call sites.
_BANNED_CLOCK = {
    "time.time", "time.monotonic", "time.monotonic_ns", "time.time_ns",
    "time.localtime", "time.gmtime",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# np.random attributes that are NOT the global-state API: seeded
# construction stays legal everywhere.
_NP_RANDOM_OK = {
    "default_rng", "Generator", "SeedSequence", "BitGenerator",
    "PCG64", "PCG64DXSM", "Philox", "SFC64", "MT19937", "RandomState",
}
# stdlib random attributes that construct an owned, seedable instance.
_STDLIB_RANDOM_OK = {"Random", "SystemRandom"}


class ClockRule(Rule):
    rule_id = "MCT002"
    title = "raw wall-clock read outside the allowlisted clock module"
    node_types = (ast.Attribute, ast.ImportFrom)

    def begin_file(self, ctx: FileContext) -> bool:
        return (ctx.rel not in ctx.manifest.clock_modules
                and not ctx.is_test)

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if isinstance(node, ast.ImportFrom):
            # `from time import monotonic` (any alias) binds a banned
            # reader to a bare name no attribute match can see — flag
            # the import. `from datetime import datetime` is fine: its
            # .now() call sites canonicalize and match below.
            for a in node.names:
                full = f"{node.module}.{a.name}" if node.module else a.name
                if node.level == 0 and full in _BANNED_CLOCK:
                    self.report(
                        ctx, node,
                        f"`from {node.module} import {a.name}` binds a "
                        "raw wall-clock reader — take an injectable "
                        "clock or use utils/clock.py",
                    )
            return
        name = dotted_name(node)
        if name is None:
            return
        if ctx.canonical(name) in _BANNED_CLOCK:
            self.report(
                ctx, node,
                f"wall-clock read {name!r} outside the clock module — "
                "take an injectable clock (perf_counter call shape; "
                "FakeClock substitutes it) or use utils/clock.py, the "
                "one sanctioned wall-clock surface",
            )


class DonationRule(Rule):
    rule_id = "MCT003"
    title = "raw donate_argnums/donate_argnames outside utils/donation.py"
    node_types = (ast.Call,)

    def begin_file(self, ctx: FileContext) -> bool:
        return ctx.rel != ctx.manifest.donation_module

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, ast.Call)
        for kw in node.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                self.report(
                    ctx, node,
                    f"{kw.arg}= spelled at a call site — donation goes "
                    "through utils/donation.donate_jit (the ONE spelling "
                    "obs.cost.assert_donation proves from the compiled "
                    "HLO alias table)",
                )


class RngRule(Rule):
    rule_id = "MCT004"
    title = "global-state RNG outside tests"
    node_types = (ast.Attribute, ast.ImportFrom)

    def begin_file(self, ctx: FileContext) -> bool:
        return not ctx.is_test

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if node.level == 0 and mod == "random":
                bad = [a.name for a in node.names
                       if a.name not in _STDLIB_RANDOM_OK]
                if bad:
                    self.report(
                        ctx, node,
                        f"`from random import {', '.join(bad)}` pulls the "
                        "process-global RNG — thread a seeded "
                        "np.random.default_rng / random.Random instead",
                    )
            elif node.level == 0 and mod == "numpy.random":
                bad = [a.name for a in node.names
                       if a.name not in _NP_RANDOM_OK]
                if bad:
                    self.report(
                        ctx, node,
                        f"`from numpy.random import {', '.join(bad)}` is "
                        "the global-state API — use default_rng(seed)",
                    )
            return
        name = dotted_name(node)
        if name is None:
            return
        # Resolve aliases through the file's own imports: `np.random.X`
        # canonicalizes to numpy.random.X; a `random` bound by
        # `from jax import random` canonicalizes to jax.random and is
        # seeded-key threading, not a violation.
        parts = ctx.canonical(name).split(".")
        if (len(parts) == 3 and parts[0] == "numpy" and parts[1] == "random"
                and parts[2] not in _NP_RANDOM_OK):
            self.report(
                ctx, node,
                f"{name} draws from numpy's process-global RNG — "
                "irreproducible; thread np.random.default_rng(seed)",
            )
        elif (len(parts) == 2 and parts[0] == "random"
                and parts[1] not in _STDLIB_RANDOM_OK):
            self.report(
                ctx, node,
                f"{name} draws from the process-global stdlib RNG — "
                "irreproducible; thread a seeded random.Random or "
                "np.random.default_rng",
            )
