"""MCT005/MCT006 — semantic cross-checks against live registries.

These two rules are the Engler move in its purest form: the codebase
already HAS the specification — obs/schema.py's EVENT_KEYS registry and
faults.py's SITES table — and the bug class is a string literal at a
call site drifting from it. A regex copy of either registry inside the
analyzer would itself drift, so the rules import the real objects: when
a family or hook site is added, the rule learns it in the same commit.

MCT005 (schema families): a string literal passed as the event family
to a record emitter (`<sink>.log("family", ...)`, `make_record("family",
t, ...)`) must be a key of obs.schema.EVENT_KEYS. An unregistered
family validates at runtime (families not in the registry are
"free-form") and then silently falls out of every consumer table —
exactly how the `bench` records emitted by bench.py and two bench
scripts went unregistered for three PRs while `mctpu compare` grew a
special case to read them.

MCT006 (fault sites): a string literal at a `<injector>.fire("site",
...)` hook point must appear in faults.SITES under some surface. This
is the static half of faults.validate_plan_sites: the runtime half
rejects a PLAN naming an unknown site at argparse time, but a typo'd
site at the EMIT side would make every plan targeting the real site
validate and then never fire — invisible until a chaos drill fails to
inject anything.
"""

from __future__ import annotations

import ast

from .core import FileContext, Rule

# Live registries — imported, not transcribed. Both home modules are
# declared jax-free in the manifest, so the analyzer stays importable
# on accelerator-less machines.
from ..faults import SITES
from ..obs.schema import EVENT_KEYS

# Emitter method names whose first positional string argument is an
# event family. `.log` covers MetricsLogger and every sink that mirrors
# its call shape; bare/attribute `make_record` covers the offline
# producers (bench scripts, tests' record builders).
_EMITTER_METHODS = {"log"}
_RECORD_BUILDERS = {"make_record"}


def _first_str_arg(node: ast.Call) -> ast.Constant | None:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0]
    return None


class SchemaFamilyRule(Rule):
    rule_id = "MCT005"
    title = "event-family literal not in obs.schema.EVENT_KEYS"
    node_types = (ast.Call,)

    def __init__(self, families=None):
        # Injectable for tests; defaults to the live registry.
        self.families = frozenset(families if families is not None
                                  else EVENT_KEYS)

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, ast.Call)
        func = node.func
        name = None
        if isinstance(func, ast.Attribute):
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        if name in _EMITTER_METHODS and isinstance(func, ast.Attribute):
            lit = _first_str_arg(node)
            # Only string first-args are family literals — loggers'
            # `.log(level, msg)` and math.log(x) never match.
            if lit is not None and lit.value not in self.families:
                self.report(
                    ctx, lit,
                    f"event family {lit.value!r} is not registered in "
                    "obs.schema.EVENT_KEYS — unregistered records "
                    "silently fall out of report/trace/compare; register "
                    "the family (with its required keys) first",
                )
        elif name in _RECORD_BUILDERS:
            lit = _first_str_arg(node)
            if lit is not None and lit.value not in self.families:
                self.report(
                    ctx, lit,
                    f"make_record family {lit.value!r} is not registered "
                    "in obs.schema.EVENT_KEYS — register it (with its "
                    "required keys) before emitting",
                )


class FaultSiteRule(Rule):
    rule_id = "MCT006"
    title = "fault hook-site literal not in faults.SITES"
    node_types = (ast.Call,)

    def __init__(self, sites=None):
        if sites is None:
            sites = {site for surface in SITES.values() for site in surface}
        self.sites = frozenset(sites)

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        assert isinstance(node, ast.Call)
        func = node.func
        if not (isinstance(func, ast.Attribute) and func.attr == "fire"):
            return
        lit = _first_str_arg(node)
        if lit is not None and lit.value not in self.sites:
            self.report(
                ctx, lit,
                f"fault hook site {lit.value!r} is not in faults.SITES — "
                "plans can never target it (validate_plan_sites rejects "
                "them), so this hook point is dead; add the site to "
                "SITES under its CLI surface(s)",
            )
