"""Training: optimizers, train/eval loops, checkpointing."""

from .optimizer import make_optimizer
from .checkpoint import (
    AsyncCheckpointer,
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from .trainer import Trainer, TrainResult, make_loss_fn

__all__ = [
    "make_optimizer",
    "AsyncCheckpointer",
    "save_checkpoint",
    "restore_checkpoint",
    "latest_checkpoint",
    "Trainer",
    "TrainResult",
    "make_loss_fn",
]
