"""Optimizer construction.

The reference's optimizer is hand-rolled SGD: gradients accumulate into
u_weights/u_biases over 32 samples, then `param -= (rate/32) * u_param`
(Layer_update cnn.c:303-314, applied at cnn.c:467-469). With a mean loss
over a batch of 32 that is exactly `sgd(lr=0.1)` on the mean gradient — the
batch-semantics equivalence SURVEY.md §7 hard-part (a) documents.

Momentum and a cosine schedule are offered beyond the reference because the
north-star accuracy target (≥99% MNIST, BASELINE.json) needs them; defaults
keep reference semantics (momentum 0, constant lr).
"""

from __future__ import annotations

import optax


def clip_grads_by_global_sq(grads, sq_norm, clip: float):
    """optax.clip_by_global_norm semantics from a PRE-COMPUTED squared
    norm: g * clip / max(norm, clip).

    The sharded-param shard_map steps (parallel/pp_lm.py,
    parallel/tp_sp.py) cannot use the optax transform — it would compute
    a per-rank PARTIAL norm — so they assemble the cross-rank squared
    norm themselves (psum of disjoint slices + replicated leaves once)
    and share this one clip application; the semantics must never drift
    between meshes.
    """
    import jax
    import jax.numpy as jnp

    norm = jnp.sqrt(sq_norm)
    scale = (clip / jnp.maximum(norm, clip)).astype(jnp.float32)
    return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)


def grad_sq(tree):
    """Sum of squared gradients across a pytree, accumulated in f32 —
    the other half of the in-step clip-norm assembly (split_grad_sq
    classifies; this reduces a bucket whose sharding is uniform)."""
    import jax
    import jax.numpy as jnp

    return sum(
        jnp.sum(jnp.square(g).astype(jnp.float32))
        for g in jax.tree.leaves(tree)
    )


def split_grad_sq(grads, specs, axis: str):
    """(sliced_sq, replicated_sq): the squared-gradient sum in f32,
    split by whether `axis` appears in each leaf's PartitionSpec.

    The one classification every sharded-param step's in-step grad-clip
    uses (parallel/tp_sp.py over 'model', parallel/sp.py's FSDP branch
    over 'data', parallel/tp_pp_lm.py over 'model' within the stacked
    blocks): sliced leaves are DISJOINT over `axis` — the caller psums
    their bucket there — while replicated leaves are identical on every
    rank of it and count once. Keeping the walk here, next to
    clip_grads_by_global_sq, means the norm accounting cannot drift
    between meshes."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    sliced = jnp.float32(0)
    rep = jnp.float32(0)
    for g, s in zip(
        jax.tree.leaves(grads),
        jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P)),
        strict=True,
    ):
        term = jnp.sum(jnp.square(g).astype(jnp.float32))
        if axis in tuple(s):
            sliced = sliced + term
        else:
            rep = rep + term
    return sliced, rep


def make_optimizer(
    lr: float = 0.1,
    *,
    opt: str = "sgd",
    momentum: float = 0.0,
    schedule: str = "constant",
    total_steps: int | None = None,
    warmup_steps: int = 0,
    weight_decay: float = 0.0,
    grad_clip: float = 0.0,  # >0: clip_by_global_norm before the update
) -> optax.GradientTransformation:
    if schedule == "constant":
        lr_sched: optax.Schedule | float = lr
    elif schedule == "cosine":
        if total_steps is None:
            raise ValueError("cosine schedule needs total_steps")
        if warmup_steps:
            lr_sched = optax.warmup_cosine_decay_schedule(
                0.0, lr, warmup_steps, total_steps
            )
        else:
            lr_sched = optax.cosine_decay_schedule(lr, total_steps)
    else:
        raise ValueError(f"unknown schedule {schedule!r}")

    if opt == "sgd":
        tx = optax.sgd(lr_sched, momentum=momentum or None)
        if weight_decay:
            tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    elif opt == "adamw":
        # The LM family's optimizer (train/lm.py); the CNN paths keep the
        # reference's SGD semantics by default.
        if momentum:
            raise ValueError(
                "momentum is an SGD knob; adamw's betas are not remapped "
                "from it — drop --momentum or use opt='sgd'"
            )
        tx = optax.adamw(lr_sched, weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown optimizer {opt!r}; 'sgd' or 'adamw'")
    if grad_clip > 0:
        tx = optax.chain(optax.clip_by_global_norm(grad_clip), tx)
    return tx
