"""Train/eval loops.

The reference's training loop (cnn.c:445-474): per-sample forward/backward
with gradients accumulated over 32 samples, update every 32nd step at
lr/32, running squared-error print every 1000 samples; eval is a forward
argmax sweep printing "ntests=%d, ncorrect=%d" (cnn.c:494-518). Here the
loop is batched (batch == the reference's accumulator period — identical
averaged gradient, SURVEY.md §7 hard-part (a)), the step is one jitted SPMD
program over the device mesh, and the host loop only feeds batches and
reads metrics.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..data.pipeline import normalize_images, one_hot
from ..models.initializers import get_initializer
from ..ops import softmax_cross_entropy, squared_error_total, stable_softmax
from ..parallel.dp import (
    dp_shard_batch,
    dp_shard_perm,
    make_dp_eval_step,
    make_dp_scan_epoch,
    make_dp_train_step,
    replicate,
)
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, make_mesh
from ..parallel.pp import (
    make_pipeline_plan,
    make_pp_forward,
    make_pp_scan_epoch,
    make_pp_state,
    make_pp_train_step,
    microbatch,
    pp_shard_batch,
)
from ..parallel.tp import (
    make_tp_eval_step,
    make_tp_scan_epoch,
    make_tp_state,
    make_tp_train_step,
)
from ..obs import cost as obs_cost
from ..obs.device import emit_step_telemetry
from ..obs.trace import span
from ..faults import (
    MAX_NAN_ROLLBACKS,
    NanGuard,
    NonFiniteLossError,
    PreemptionGuard,
    RollbackToCheckpoint,
    all_finite,
    drain_preemption,
    poison_batch,
    step_is_finite,
)
from ..obs.metrics import MetricsRegistry
from ..parallel.distributed import barrier, process_info
from ..utils.logging import MetricsLogger, get_logger
from ..utils.profiling import StepTimer, profile_trace
from ..utils.sync import hard_block
from .checkpoint import (
    AsyncCheckpointer,
    restore_latest,
    validate_resume_meta,
)
from .optimizer import make_optimizer


def make_loss_fn(model, *, backend: str = "xla", compute_dtype=None,
                 remat: bool = False):
    """Softmax-CE loss + the reference's metrics (squared-error total,
    cnn.c:275-282; argmax accuracy, cnn.c:508-513)."""

    def loss_fn(params, x, y_onehot):
        logits = model.apply(params, x, backend=backend,
                             compute_dtype=compute_dtype, remat=remat)
        loss = softmax_cross_entropy(logits, y_onehot)
        probs = stable_softmax(logits)
        acc = jnp.mean(
            (jnp.argmax(logits, -1) == jnp.argmax(y_onehot, -1)).astype(jnp.float32)
        )
        return loss, {"etotal": squared_error_total(probs, y_onehot), "acc": acc}

    return loss_fn


@dataclasses.dataclass
class TrainResult:
    epochs_run: int
    final_step: int
    test_accuracy: float
    ntests: int
    ncorrect: int
    epoch_seconds: list[float]
    mean_step_ms: float


class Trainer:
    """End-to-end trainer: model + dataset + mesh -> trained params.

    Single-device and multi-device use the same code path: a 1-device mesh
    makes the DP collectives identity ops, so the SPMD program is the only
    train step there is.
    """

    def __init__(self, model, dataset, config, *, mesh=None,
                 metrics: MetricsLogger | None = None, faults=None,
                 preempt: PreemptionGuard | None = None, registry=None,
                 clock=None):
        self.model = model
        self.ds = dataset
        self.cfg = config
        self.log = get_logger()
        self.metrics = metrics or MetricsLogger()
        # Runtime metrics registry (ISSUE 6): step-time histogram,
        # samples/s gauge, liveness counters. The CLI passes ONE shared
        # registry so totals (steps, restarts) survive supervisor
        # rebuilds; standalone construction gets a private one.
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        # `clock` has the time.perf_counter call shape and is the time
        # source for epoch wall-clocks and the step timers feeding the
        # registry fold — a FakeClock makes telemetry deterministic (the
        # PR-4 contract). device_epoch_seconds stays on real wall time:
        # it measures hardware, not telemetry.
        self._clock = clock if clock is not None else time.perf_counter
        # Fault hooks + the NaN/Inf guard (ISSUE 4). `faults` is a
        # faults.FaultInjector; the CLI builds one from --fault-plan and
        # shares it across supervisor restarts (fired faults stay fired).
        # The guard's policy rules live in faults.NanGuard — ONE
        # implementation for this trainer and the LM's.
        self.faults = faults
        # Preemption guard (ISSUE 5): the CLI installs one on
        # SIGTERM/SIGINT and shares it; an un-installed default still
        # answers injected `preempt@train.step` faults, so elasticity
        # tests never touch real signals.
        self._preempt = preempt if preempt is not None else PreemptionGuard()
        self._nan = NanGuard(getattr(config, "nan_policy", "off"),
                             getattr(config, "nan_max_bad", 3))
        self._finite_fn = jax.jit(all_finite) if self._nan.active else None

        ndev = config.num_devices or len(jax.devices())
        if mesh is None:
            from ..utils.config import parse_mesh_shape

            axes = parse_mesh_shape(config.mesh_shape, ndev)
            mesh = make_mesh(axes, devices=jax.devices()[:ndev])
        self.mesh = mesh
        n_data = self.mesh.shape.get(DATA_AXIS, 1)
        if config.batch_size % n_data:
            raise ValueError(
                f"batch_size {config.batch_size} not divisible by data-axis size {n_data}"
            )
        if config.grad_accum > 1 and (config.batch_size // n_data) % config.grad_accum:
            raise ValueError(
                f"per-device batch {config.batch_size // n_data} not divisible "
                f"by grad_accum {config.grad_accum}"
            )
        if config.elastic_width:
            # Width-invariant reduction rides the plain shard_map DP
            # step only: sharded-param layouts (TP/FSDP/PP) change WHAT
            # is reduced with the width, not just how — cross-width
            # bitwise resume is out of reach there by construction.
            from ..parallel.elastic import check_elastic_width

            if (self.mesh.shape.get(MODEL_AXIS, 1) > 1
                    or self.mesh.shape.get(PIPE_AXIS, 1) > 1
                    or config.fsdp):
                raise ValueError(
                    "--elastic-width needs a pure data-parallel mesh "
                    f"(mesh_shape={config.mesh_shape!r}/--fsdp shard "
                    "params; cross-width bitwise resume is only defined "
                    "for replicated state)"
                )
            if config.grad_accum > 1:
                raise ValueError(
                    "--elastic-width already scans canonical "
                    "microbatches; --grad-accum is redundant with it — "
                    "drop one of the two"
                )
            check_elastic_width(config.elastic_width, config.batch_size,
                                n_data)

        compute_dtype = (
            jnp.bfloat16 if config.compute_dtype == "bfloat16" else None
        )
        backend = "pallas" if config.use_pallas else "xla"
        self.loss_fn = make_loss_fn(model, backend=backend,
                                    compute_dtype=compute_dtype,
                                    remat=config.remat)

        from ..data.augment import make_augment

        self._augment = make_augment(config.augment, pad=config.aug_pad)
        # fold_in needs a distinct stream from param init; offset the seed.
        self._aug_seed = config.seed + 0x5EED

        # Normalized host copies are built lazily (train_x/train_y
        # properties): the default scanned path stages raw uint8 on device
        # and never needs the float32 host materialization.
        self._train_x = None
        self._train_y = None
        self.num_train = len(dataset.train_images)
        self.test_x = normalize_images(dataset.test_images)
        self.test_labels = np.asarray(dataset.test_labels)

        self.steps_per_epoch = self.num_train // config.batch_size
        total_steps = self.steps_per_epoch * config.epochs
        # The pipelined step clips IN-STEP with a cross-rank-correct
        # global norm (its packed rows are sharded, so optax's
        # clip_by_global_norm would compute a per-rank partial norm) —
        # same split as the LM trainer's sharded-param paths.
        pp_clip = self.mesh.shape.get(PIPE_AXIS, 1) > 1
        self.optimizer = make_optimizer(
            config.lr,
            momentum=config.momentum,
            schedule=config.lr_schedule,
            total_steps=total_steps or None,
            grad_clip=0.0 if pp_clip else config.grad_clip,
        )

        # One keyed init, replicated to every device (fixes the reference's
        # divergent never-synchronized per-rank init, SURVEY.md 2.6c).
        init = get_initializer(config.init)
        param_dtype = jnp.dtype(config.param_dtype)
        params = model.init(jax.random.key(config.seed), init, dtype=param_dtype)
        predict = lambda params, x: model.apply(
            params, x, backend=backend, compute_dtype=compute_dtype
        )
        self.n_model = self.mesh.shape.get(MODEL_AXIS, 1)
        self.n_pipe = self.mesh.shape.get(PIPE_AXIS, 1)
        self._pp_M = 1  # microbatches per step; >1 only on the PP path
        if self.n_pipe == 1 and config.num_microbatches:
            raise ValueError(
                "--num-microbatches requires a 'pipe' mesh axis "
                f"(mesh_shape={config.mesh_shape!r} has none)"
            )
        if self.n_pipe > 1:
            # Pipeline(+data) parallel: stage-sharded params, GPipe
            # microbatch schedule (parallel/pp.py). Beyond the reference,
            # which runs layers sequentially in one process (cnn.c:255-267).
            # Composes with --augment (applied in the step body, keyed like
            # the DP path), --remat (jax.checkpoint per stage), --fsdp
            # (ZeRO sharding of the packed stage rows over 'data'), and TP.
            if config.grad_accum > 1:
                raise ValueError(
                    "--grad-accum is redundant on the pipeline path: "
                    "--num-microbatches already accumulates over "
                    "micro-batches"
                )
            if param_dtype != jnp.float32:
                raise ValueError(
                    "pipeline parallelism keeps master params in the packed "
                    "f32 stage buffers; use --compute-dtype for low-precision "
                    f"compute (got param_dtype={config.param_dtype})"
                )
            if config.fsdp and n_data <= 1:
                raise ValueError(
                    "FSDP x PP shards the packed stage rows over 'data'; "
                    f"add a data axis of size > 1 (mesh_shape="
                    f"{config.mesh_shape!r})"
                )
            self._pp_M = config.num_microbatches or self.n_pipe
            if config.batch_size % (self._pp_M * n_data):
                raise ValueError(
                    f"batch_size {config.batch_size} not divisible by "
                    f"num_microbatches x data-axis ({self._pp_M} x {n_data})"
                )
            self._pp_plan = make_pipeline_plan(
                model, self.n_pipe, backend=backend,
                compute_dtype=compute_dtype, n_model=self.n_model,
                remat=config.remat,
                fsdp_degree=n_data if config.fsdp else 1,
            )
            self.state = make_pp_state(
                self._pp_plan, params, self.optimizer, self.mesh
            )
            self.train_step = make_pp_train_step(
                self._pp_plan, self.optimizer, self.mesh, self.state,
                donate=config.donate,
                augment=self._augment, aug_seed=self._aug_seed,
                grad_clip=config.grad_clip,
            )
            self.eval_step = make_pp_forward(self._pp_plan, self.mesh)
        elif self.n_model > 1 or config.fsdp:
            # GSPMD paths — sharding lives in the STATE PLACEMENT, the
            # step is the plain jitted one and XLA inserts the
            # collectives: TP shards params over 'model' (parallel/tp.py;
            # the reference has no TP at all, SURVEY.md §2 checklist),
            # FSDP shards params + optimizer state ZeRO-style over the
            # same 'data' axis as the batch (parallel/fsdp.py).
            if config.fsdp:
                from ..parallel.fsdp import make_fsdp_state

                base = None
                if self.n_model > 1:
                    # FSDP x TP: features over 'model' (Megatron), the
                    # largest remaining dim over 'data' (ZeRO).
                    from ..parallel.tp import tp_param_specs

                    base = tp_param_specs(model, self.mesh)
                self.state = make_fsdp_state(
                    params, self.optimizer, self.mesh, base_specs=base
                )
            else:
                self.state = make_tp_state(
                    model, params, self.optimizer, self.mesh
                )
            self.train_step = make_tp_train_step(
                self.loss_fn, self.optimizer, donate=config.donate,
                augment=self._augment, aug_seed=self._aug_seed,
                grad_accum=config.grad_accum,
            )
            self.eval_step = make_tp_eval_step(predict)
        else:
            opt_state = self.optimizer.init(params)
            self.state = replicate(
                {"params": params, "opt_state": opt_state,
                 "step": jnp.zeros((), jnp.int32)},
                self.mesh,
            )
            self.train_step = make_dp_train_step(
                self.loss_fn, self.optimizer, self.mesh, donate=config.donate,
                augment=self._augment, aug_seed=self._aug_seed,
                grad_accum=config.grad_accum,
                elastic_width=config.elastic_width,
            )
            self.eval_step = make_dp_eval_step(predict, self.mesh)
        # Scanned-epoch path: built lazily on first use (run_epoch), since
        # it stages the uint8 training set into device memory.
        self._scan_epoch_fn = None
        self._dev_images = None
        self._dev_labels = None
        self._eval_batch = self._pick_eval_batch(
            len(self.test_x), n_data * self._pp_M
        )
        # Shuffle order is a pure function of (seed, epoch) — see
        # _epoch_order — so every entry point (train(), run_epoch() via
        # the C ABI, a resumed process after preemption) reconstructs the
        # exact batch order without any serialized RNG state. This is what
        # makes STEP-granular resume bitwise-exact (SURVEY.md §5.3/5.4
        # "elastic recovery"): epoch = step // steps_per_epoch, position
        # = step % steps_per_epoch, order = _epoch_order(epoch).

        if self.steps_per_epoch == 0:
            raise ValueError(
                f"batch_size {config.batch_size} exceeds train set size "
                f"{self.num_train}: no full batches"
            )

        # Telemetry: compiled-program accounting is emitted once per
        # program label (obs.cost — an extra AOT compile, so only when a
        # JSONL sink wants it); per-epoch phase/memory records ride the
        # same gate.
        self._programs_logged: set[str] = set()

        # One checkpointer for every save site; async by default (the
        # step loop pays only the host snapshot, the npz write overlaps
        # the next steps; train() drains it before returning). Each
        # checkpoint's manifest entry records the topology it was
        # written under (mesh axes + elastic width — what a
        # topology-changed resume validates against), and on multihost
        # runs process 0 is the only writer with a barrier fencing the
        # publication (train/checkpoint.py).
        from ..parallel.mesh import describe_mesh

        self._proc = process_info()
        self._ckpt_meta = {
            "mesh": describe_mesh(self.mesh),
            "elastic_width": config.elastic_width,
            "process_count": self._proc.process_count,
        }
        self._ckpt = (
            AsyncCheckpointer(config.checkpoint_dir,
                              async_=config.async_checkpoint,
                              faults=faults, meta=self._ckpt_meta,
                              process=self._proc, barrier=barrier)
            if config.checkpoint_dir else None
        )

    def _epoch_order(self, epoch: int) -> np.ndarray:
        """The epoch's sample permutation — derived, never stored."""
        return np.random.default_rng((self.cfg.seed, epoch)).permutation(
            self.num_train
        )

    def _global_step(self) -> int:
        return int(jax.device_get(self.state["step"]))

    def _maybe_step_checkpoint(self, global_step: int) -> None:
        """Mid-epoch save when --checkpoint-every-steps divides the global
        step (called at batch/chunk boundaries; the host-side step count
        avoids a per-step device sync — saving itself syncs)."""
        cfg = self.cfg
        if not (cfg.checkpoint_dir and cfg.checkpoint_every_steps):
            return
        if global_step and global_step % cfg.checkpoint_every_steps == 0:
            self._ckpt.save(self.state, global_step)

    def _drain_fault_events(self) -> None:
        """Forward the injector's fired-fault records to the obs sink."""
        if self.faults is not None:
            for ev in self.faults.drain_events():
                self.metrics.log("fault", **ev)

    def _step_boundary(self, global_step: int) -> None:
        """The per-step fault/preemption hook shared by the loop and
        scanned paths: fire planned train.step faults (an injected
        ``preempt`` sets the same flag a real SIGTERM would), then
        drain the orderly-exit path (faults.drain_preemption — ONE
        implementation for this trainer and the LM's) if a preemption
        is pending."""
        if self.faults is not None:
            for f in self.faults.fire("train.step", global_step):
                if f.kind == "preempt":
                    self._preempt.request()
            self._drain_fault_events()
        drain_preemption(self._preempt, state=self.state,
                         global_step=global_step, ckpt=self._ckpt,
                         metrics=self.metrics, logger=self.log)

    def _drop_bad_update(self, gstep: int, snap) -> None:
        """Apply --nan-policy to a non-finite step (faults.NanGuard owns
        the rules; abort and rollback raise there). A plain skip drops
        the bad update by reinstalling the pre-step snapshot — with the
        step counter still ADVANCED past the dropped batch:
        state["step"] must stay equal to batches CONSUMED, or a later
        crash-restart / rollback would re-derive its resume position
        short by the skipped steps and replay already-applied batches
        (breaking the bitwise restart contract). An organic NaN replays
        deterministically to the same skip, so positions stay exact."""
        self._nan.bad_step(gstep, logger=self.log, metrics=self.metrics)
        snap = dict(snap)
        snap["step"] = np.asarray(snap["step"]) + 1
        self.place_state(snap)

    def _rollback_to_checkpoint(self) -> tuple[int, int]:
        """Reload the newest valid checkpoint after a nan-policy=restore
        rollback; returns the (epoch, skip_steps) to re-enter at."""
        if self._ckpt is not None:
            self._ckpt.wait()  # the in-flight write may BE the newest
        restored, path = restore_latest(
            self.cfg.checkpoint_dir or "", jax.device_get(self.state),
            logger=self.log, metrics=self.metrics,
        ) if self.cfg.checkpoint_dir else (None, None)
        if restored is None:
            raise NonFiniteLossError(
                "nan-policy=restore: no valid checkpoint to roll back to "
                "(set --checkpoint-dir and --checkpoint-every-steps)"
            )
        self.place_state(restored)
        self._nan.step_ok()
        spe = max(self.steps_per_epoch, 1)
        step0 = self._global_step()
        self.metrics.log("fault", kind="nan_restore", step=step0,
                         path=path.name)
        self.log.warning("nan-policy=restore: rolled back to %s (step %d)",
                         path, step0)
        return step0 // spe, step0 % spe

    def _maybe_log_program(self, label: str, fn, *args,
                           steps_per_dispatch: int = 1,
                           counting: str = "program") -> None:
        """Emit ONE "program" record per program label: FLOPs/bytes from
        XLA cost analysis of the step actually dispatched, collectives
        from its HLO (obs.cost). Costs an extra AOT compile, so gated on
        the JSONL sink; failures degrade to a warning."""
        if self.metrics is None or not self.metrics.jsonl_enabled:
            return
        if label in self._programs_logged:
            return
        self._programs_logged.add(label)
        if not obs_cost.log_program(
            self.metrics, label, fn, *args,
            steps_per_dispatch=steps_per_dispatch, counting=counting,
            compute_dtype=self.cfg.compute_dtype,
        ):
            self.log.warning("obs: cost analysis unavailable for %r", label)

    def _emit_epoch_obs(self, epoch: int, timer: StepTimer,
                        nsteps: int) -> None:
        """Per-epoch telemetry (the shared obs.device emit path), plus
        the runtime-registry fold (ISSUE 6): step-time histogram,
        samples/s gauge, and liveness counters — what `mctpu top`
        renders and `mctpu compare` gates. Aggregation consumes only the
        timer's already-measured intervals (no clock reads here), so a
        FakeClock-driven timer yields bitwise-identical snapshots."""
        emit_step_telemetry(self.metrics, timer, nsteps,
                            devices=list(self.mesh.devices.flat),
                            epoch=epoch)
        if nsteps <= 0:
            return
        reg = self.registry
        reg.inc("train.steps", nsteps)
        reg.inc("train.heartbeats")
        step_ms = timer.mean_step_ms
        reg.observe("train.step_ms", step_ms)
        if step_ms > 0:
            reg.set("train.samples_per_s",
                    1e3 * self.cfg.batch_size / step_ms)
        reg.emit(self.metrics, epoch=epoch)

    @staticmethod
    def _pick_eval_batch(ntest: int, granularity: int, target: int = 2048) -> int:
        """Largest eval batch <= target divisible by `granularity` (the
        data-axis size, times the microbatch count on the PP path)."""
        b = min(target, ntest)
        b -= b % granularity
        return max(b, granularity)

    def _place_batch(self, bx, by):
        """Put one host batch on the mesh in the layout the active train
        step expects: (M, mb, ...) microbatches for PP, a flat sharded
        batch otherwise."""
        bx, by = jnp.asarray(bx), jnp.asarray(by)
        if self.n_pipe > 1:
            return pp_shard_batch(microbatch(bx, by, self._pp_M), self.mesh)
        return dp_shard_batch((bx, by), self.mesh)

    @property
    def train_x(self):
        """Normalized float32 host copy, built on first use (the per-batch
        loop path); the scanned path works from the uint8 device copy."""
        if self._train_x is None:
            self._train_x = normalize_images(self.ds.train_images)
        return self._train_x

    @property
    def train_y(self):
        if self._train_y is None:
            self._train_y = one_hot(self.ds.train_labels, self.ds.num_classes)
        return self._train_y

    # ------------------------------------------------------------------

    def place_state(self, host_state) -> None:
        """Install a host-side state pytree (e.g. a restored checkpoint)
        with the SAME shardings the live state uses — replicated on the DP
        path, model-axis-sharded on the TP path. Checkpoints store full
        arrays, so restore must re-place, not just replicate."""
        shardings = jax.tree.map(lambda a: a.sharding, self.state)
        self.state = jax.device_put(host_state, shardings)

    def _dataset_bytes(self) -> int:
        """What the scanned path would stage: uint8 pixels + int32 labels."""
        return self.ds.train_images.nbytes + 4 * self.num_train

    def _oversized(self) -> bool:
        return self._dataset_bytes() > self.cfg.scan_max_bytes

    def _use_scan(self) -> bool:
        """Scanned epochs stage the WHOLE uint8 training set in HBM; for
        datasets past --scan-max-bytes that is the wrong trade — fall back
        to the streaming per-batch path (host feeds one batch per step),
        which bounds device memory at O(batch) regardless of dataset
        size. Identical math either way (test_scan_and_loop_paths_...)."""
        if not self.cfg.scan:
            return False
        if self.faults is not None and any(
            f.site == "train.batch" for f in self.faults.plan
        ):
            # A planned batch fault can only fire on the per-batch loop
            # (the scanned epoch builds batches on device); silently
            # no-op'ing the injection would let a chaos run believe it
            # exercised a fault that never happened.
            if not getattr(self, "_fault_scan_logged", False):
                self._fault_scan_logged = True
                self.log.warning(
                    "fault plan targets train.batch: per-batch stepping "
                    "(scanned epochs cannot inject batch faults)"
                )
            return False
        if self._nan.active:
            # The guard checks loss/metrics and state finiteness per
            # STEP (skip must drop exactly the bad update); the scanned
            # epoch dispatches many steps at once, so guarded runs step
            # per batch. Robustness mode trades throughput knowingly.
            if not getattr(self, "_nan_scan_logged", False):
                self._nan_scan_logged = True
                self.log.warning(
                    "--nan-policy=%s active: per-batch stepping (the "
                    "scanned epoch cannot skip/rollback single steps)",
                    self.cfg.nan_policy,
                )
            return False
        if self._oversized():
            if not getattr(self, "_scan_fallback_logged", False):
                self._scan_fallback_logged = True
                self.log.warning(
                    "dataset is %.1f GiB > --scan-max-bytes %.1f GiB: "
                    "streaming per-batch epochs instead of HBM staging",
                    self._dataset_bytes() / 2**30,
                    self.cfg.scan_max_bytes / 2**30,
                )
            return False
        return True

    def run_epoch(self, epoch: int, *, skip_steps: int = 0) -> dict:
        """Run one epoch of the jitted step over the whole training set.

        The single implementation behind both the Python CLI loop (train())
        and the C driver's ABI (runtime_abi.train_epoch) — one derived
        shuffle order (_epoch_order), one metric scheme. skip_steps > 0
        resumes MID-epoch: the first skip_steps batches of this epoch's
        order are skipped (they ran before the preemption). Metric sums
        accumulate as device scalars: no host sync per step, so dispatch
        stays async (the reference blocks on every sample by construction;
        we must not).
        """
        if self._use_scan():
            return self._run_epoch_scanned(epoch, skip_steps=skip_steps)
        cfg = self.cfg
        t0 = self._clock()
        running = None
        nsteps = 0
        order = self._epoch_order(epoch)
        b = cfg.batch_size
        timer = StepTimer(clock=self._clock)
        timer.start()
        # Oversized datasets normalize PER BATCH: the cached train_x/train_y
        # copies are a 4x float32 blow-up of the whole set — the exact host
        # materialization this path exists to avoid (see _use_scan).
        stream = self._oversized()
        labels = np.asarray(self.ds.train_labels) if stream else None
        ngood = 0  # steps whose update was kept (== nsteps unguarded)
        for start in range(skip_steps * b, self.num_train - self.num_train % b, b):
            idx = order[start : start + b]
            # 0-based global index of the step ABOUT to run; +1 below is
            # the completed-step count the checkpoint/crash hooks see.
            gstep = epoch * self.steps_per_epoch + skip_steps + nsteps
            with timer.phase("data"):
                if stream:
                    bx = normalize_images(self.ds.train_images[idx])
                    by = one_hot(labels[idx], self.ds.num_classes)
                else:
                    bx, by = self.train_x[idx], self.train_y[idx]
                if self.faults is not None:
                    for f in self.faults.fire("train.batch", gstep):
                        if f.kind == "nan":
                            bx = poison_batch(bx, f)
                            self._drain_fault_events()
                batch = self._place_batch(bx, by)
            if nsteps == 0:
                # exclude(): the analysis costs an AOT compile that must
                # not land in the step-phase attribution it feeds.
                with timer.exclude():
                    self._maybe_log_program("train_step", self.train_step,
                                            self.state, *batch)
            # skip/restore must be able to DROP the update: hold a host
            # snapshot of the pre-step state (donation consumes the
            # device buffers). Guard-only cost, documented in README.
            snap = jax.device_get(self.state) if self._nan.snapshots else None
            with timer.phase("dispatch"):
                self.state, m = self.train_step(self.state, *batch)
            nsteps += 1
            if self._nan.active and not step_is_finite(m, self._finite_fn,
                                                       self.state):
                # Drop the update (abort/rollback raise inside); the
                # checkpoint + crash hooks below still run — a skipped
                # step consumed its batch, and a planned fault at this
                # step value must not silently evaporate.
                self._drop_bad_update(gstep, snap)
            else:
                self._nan.step_ok()
                running = (m if running is None
                           else jax.tree.map(jnp.add, running, m))
                ngood += 1
                # step is the ABSOLUTE in-epoch position (skip included)
                # so a resumed run's metric stream lines up with the
                # scanned path's.
                if cfg.log_every > 0 and \
                        (skip_steps + nsteps) % cfg.log_every == 0:
                    with timer.phase("device"):
                        jax.block_until_ready(running)
                    self.metrics.log(
                        "train",
                        epoch=epoch,
                        step=skip_steps + nsteps,
                        loss=float(running["loss"]) / ngood,
                        etotal=float(running["etotal"]) / ngood,
                        acc=float(running["acc"]) / ngood,
                    )
                    # Loss as a registry gauge (ISSUE 8): health/top
                    # read it off `metrics` snapshots, with the min/max
                    # envelope the train record alone cannot carry.
                    self.registry.set("train.loss",
                                      float(running["loss"]) / ngood)
            with timer.phase("checkpoint"):
                self._maybe_step_checkpoint(gstep + 1)
            self._step_boundary(gstep + 1)
        # hard_block, not block_until_ready: the epoch wall-clock must
        # cover the COMPUTE, and under this env's remote-TPU tunnel
        # block_until_ready returns at enqueue (utils/sync.py).
        with timer.phase("device"):
            hard_block(self.state)
        # Subtract the obs AOT-compile time the timer excluded, so the
        # epoch record and step_phases record cannot disagree.
        seconds = self._clock() - t0 - timer.excluded_s
        timer.stop(max(nsteps, 1))
        self._emit_epoch_obs(epoch, timer, nsteps)
        if nsteps == 0:
            raise ValueError(
                f"no full batches: train set of {self.num_train} yields "
                f"0 batches of {cfg.batch_size}"
            )
        # Guarded epochs can drop every update (running is None): report
        # NaN metrics rather than crash — the fault events carry the why.
        return {
            "epoch": epoch,
            "steps": nsteps,
            "loss": float(running["loss"]) / ngood if ngood else float("nan"),
            "etotal": float(running["etotal"]) / ngood if ngood else float("nan"),
            "acc": float(running["acc"]) / ngood if ngood else float("nan"),
            "seconds": seconds,
        }

    def _stage_dataset(self):
        """Place the raw uint8 training set + int32 labels in device memory,
        replicated, once per run. HBM cost is the uint8 pixels (e.g. 47 MB
        for MNIST) — normalization/one-hot happen inside the scanned step."""
        from ..data.pipeline import ensure_channel_axis

        images = ensure_channel_axis(self.ds.train_images)
        self._dev_images = replicate(jnp.asarray(images, jnp.uint8), self.mesh)
        self._dev_labels = replicate(
            jnp.asarray(self.ds.train_labels, jnp.int32), self.mesh
        )
        if self.n_pipe > 1:
            self._scan_epoch_fn = make_pp_scan_epoch(
                self._pp_plan, self.optimizer, self.mesh, self.state,
                self.ds.num_classes, self._pp_M, donate=self.cfg.donate,
                augment=self._augment, aug_seed=self._aug_seed,
                grad_clip=self.cfg.grad_clip,
            )
        elif self.n_model > 1 or self.cfg.fsdp:
            # Both GSPMD paths (TP-sharded or FSDP-sharded params) scan
            # with the plain jitted epoch; shardings flow from the state.
            self._scan_epoch_fn = make_tp_scan_epoch(
                self.loss_fn, self.optimizer, self.ds.num_classes,
                donate=self.cfg.donate,
                augment=self._augment, aug_seed=self._aug_seed,
                grad_accum=self.cfg.grad_accum,
            )
        else:
            self._scan_epoch_fn = make_dp_scan_epoch(
                self.loss_fn, self.optimizer, self.mesh, self.ds.num_classes,
                donate=self.cfg.donate,
                augment=self._augment, aug_seed=self._aug_seed,
                grad_accum=self.cfg.grad_accum,
                elastic_width=self.cfg.elastic_width,
            )

    def device_epoch_seconds(self, *, reps: int = 3, k: int = 2,
                             min_signal_s: float = 0.015,
                             budget_s: float | None = None) -> float | None:
        """On-device steady-state epoch seconds via the shared two-point
        recipe (utils/sync.two_point): k scanned epochs dispatched
        back-to-back with ONE hard sync, so (T(2k)-T(k))/k cancels any
        fixed per-window cost — under this environment's remote-TPU
        tunnel that is the ~100-300 ms dispatch round-trip dominating a
        single epoch's wall-clock. The ONE implementation behind
        bench.py's `device_epoch_s` field and bench_configs' primary
        column (the recipe must not drift per caller — that per-script
        drift caused every shipped measurement bug, utils/sync.py).

        Runs ~reps*(3k)+1 extra epochs, advancing self.state (harmless
        for a timing run) — and up to reps*48 MORE when the sub-15 ms
        retry re-measures at k=16. budget_s caps the TOTAL wall-clock:
        the retry is skipped (returning None) when its projected cost
        would overrun it, so a caller's attempt timeout can't be eaten
        by the re-measure path (bench.py's guard used to size only the
        first pass — ADVICE round 5). Returns None on a non-TPU backend
        (the recipe exists to cancel the TPU tunnel's dispatch window;
        on CPU the wall-clock is already honest and the extra epochs
        would dominate the caller's run), when the scanned path isn't
        staged (streaming fallback), or when the slope stays
        non-positive (a backend transient) — callers fall back to
        wall-clock."""
        from ..utils.sync import two_point

        if jax.default_backend() != "tpu":
            return None
        if not self._use_scan() or self._scan_epoch_fn is None:
            return None
        b = self.cfg.batch_size
        nsteps = self.steps_per_epoch
        perm = (self._epoch_order(0)[: nsteps * b]
                .reshape(nsteps, b).astype(np.int32))
        rows = dp_shard_perm(perm, self.mesh)

        def run(m):
            t0 = time.perf_counter()
            sums = None
            for _ in range(m):
                # Thread self.state so donated buffers stay valid.
                self.state, sums = self._scan_epoch_fn(
                    self.state, self._dev_images, self._dev_labels, rows
                )
            hard_block(sums)
            return time.perf_counter() - t0

        t0 = time.perf_counter()
        est = two_point(run, k, warmup=1, reps=reps)
        if est < min_signal_s:
            # Sub-15 ms epochs leave the window diff inside tunnel
            # jitter; re-measure with ~100 ms of signal per window. A
            # NEGATIVE first slope is the same artifact class and gets
            # the same retry (not an early None).
            if budget_s is not None:
                # The retry runs reps*3*16 epochs vs the first pass's
                # 1 + reps*3*k — project its cost from what the first
                # pass actually took and skip when it would overrun.
                elapsed = time.perf_counter() - t0
                projected = elapsed * (reps * 48) / (1 + reps * 3 * k)
                if elapsed + projected > budget_s:
                    return None
            est = two_point(run, 16, warmup=0, reps=reps)
        return est if est > 0 else None

    def _run_epoch_scanned(self, epoch: int, *, skip_steps: int = 0) -> dict:
        """Scanned epoch: one device dispatch per `log_every` steps (one per
        epoch when logging is off). The host sends only the int32 batch
        permutation; the dataset stays HBM-resident across epochs.
        skip_steps resumes mid-epoch; --checkpoint-every-steps additionally
        splits chunks at checkpoint boundaries so mid-epoch saves land on
        exact step counts."""
        cfg = self.cfg
        t0 = self._clock()
        timer = StepTimer(clock=self._clock)
        timer.start()
        with timer.phase("data"):
            if self._scan_epoch_fn is None:
                self._stage_dataset()
            b = cfg.batch_size
            nsteps = self.steps_per_epoch
            order = self._epoch_order(epoch)[: nsteps * b]
            perm = order.reshape(nsteps, b).astype(np.int32)

        # log_every <= 0 means logging off -> the whole epoch is one scan.
        # A shorter tail chunk costs one extra (cached thereafter) compile.
        chunk = nsteps if cfg.log_every <= 0 else min(cfg.log_every, nsteps)
        log_chunks = 0 < cfg.log_every <= nsteps  # parity with the loop path
        totals = None
        done = skip_steps
        while done < nsteps:
            end = min(done + chunk - done % chunk, nsteps)
            if cfg.checkpoint_dir and cfg.checkpoint_every_steps:
                # Break the chunk at the next global checkpoint boundary
                # (gated like _maybe_step_checkpoint — no dir, no split).
                # Chunk shapes recur once boundary offsets cycle; choosing
                # --checkpoint-every-steps to divide steps_per_epoch keeps
                # the XLA shape/compile set at its minimum.
                gstep = epoch * nsteps + done
                nxt = gstep + (
                    cfg.checkpoint_every_steps - gstep % cfg.checkpoint_every_steps
                )
                end = min(end, nxt - epoch * nsteps)
            with timer.phase("data"):
                rows = dp_shard_perm(perm[done:end], self.mesh)
            with timer.exclude():  # AOT compile out of the attribution
                # counting="static-body": XLA counts the scan body ONCE
                # (obs/cost.py docstring), so the record's flops are ~one
                # step's — steps_per_dispatch=1 keeps per-step math right.
                self._maybe_log_program(
                    "scan_epoch", self._scan_epoch_fn, self.state,
                    self._dev_images, self._dev_labels, rows,
                    steps_per_dispatch=1, counting="static-body",
                )
            with timer.phase("dispatch"):
                self.state, sums = self._scan_epoch_fn(
                    self.state, self._dev_images, self._dev_labels, rows
                )
            totals = sums if totals is None else jax.tree.map(jnp.add, totals, sums)
            done = end
            # Parity with the loop path: log only at exact multiples of
            # log_every (a short tail chunk trains but does not log).
            if log_chunks and done % cfg.log_every == 0:
                with timer.phase("device"):
                    jax.block_until_ready(totals)
                run = done - skip_steps
                self.metrics.log(
                    "train",
                    epoch=epoch,
                    step=done,
                    loss=float(totals["loss"]) / run,
                    etotal=float(totals["etotal"]) / run,
                    acc=float(totals["acc"]) / run,
                )
                # Same gauge as the loop path (ISSUE 8).
                self.registry.set("train.loss",
                                  float(totals["loss"]) / run)
            with timer.phase("checkpoint"):
                self._maybe_step_checkpoint(epoch * nsteps + done)
            # Scanned epochs advance chunk-by-chunk: crash/preempt
            # faults fire at chunk/checkpoint boundaries, where the
            # step count is exact (align `at` with a boundary) — and a
            # real SIGTERM drains here too, after the in-flight chunk.
            self._step_boundary(epoch * nsteps + done)
        with timer.phase("device"):
            hard_block(self.state)  # see run_epoch: must wait for compute
        seconds = self._clock() - t0 - timer.excluded_s  # see run_epoch
        run = nsteps - skip_steps
        timer.stop(max(run, 1))
        self._emit_epoch_obs(epoch, timer, run)
        return {
            "epoch": epoch,
            "steps": run,
            "loss": float(totals["loss"]) / run,
            "etotal": float(totals["etotal"]) / run,
            "acc": float(totals["acc"]) / run,
            "seconds": seconds,
        }

    def train(self) -> TrainResult:
        cfg = self.cfg
        start_epoch = 0
        skip_steps = 0  # mid-epoch resume position within start_epoch

        if cfg.resume and cfg.checkpoint_dir:
            host_state = jax.device_get(self.state)
            # restore_latest walks past corrupt checkpoints (manifest
            # crc32 verification) to the newest one that restores clean.
            restored, ckpt = restore_latest(cfg.checkpoint_dir, host_state,
                                            logger=self.log,
                                            metrics=self.metrics)
            if restored is not None:
                validate_resume_meta(ckpt, mesh=self.mesh,
                                     elastic_width=cfg.elastic_width,
                                     metrics=self.metrics, logger=self.log)
                self.place_state(restored)
                # The checkpoint this run stands on must survive every
                # later prune: a crash before the NEXT save would
                # otherwise have no valid restore point behind it.
                if self._ckpt is not None:
                    self._ckpt.protect = ckpt.name
                spe = max(self.steps_per_epoch, 1)
                step0 = self._global_step()
                start_epoch = step0 // spe
                skip_steps = step0 % spe
                self.metrics.log("ckpt", step=step0, reason="resume",
                                 path=ckpt.name)
                self.log.info(
                    "resumed from %s at epoch %d step %d (in-epoch %d)",
                    ckpt, start_epoch, step0, skip_steps,
                )

        timer = StepTimer(clock=self._clock)
        epoch_seconds: list[float] = []
        result_acc, ncorrect = 0.0, 0
        rollbacks = 0

        try:
            with profile_trace(cfg.profile_dir):
                epoch = start_epoch
                while epoch < cfg.epochs:
                    try:
                        em = self.run_epoch(epoch, skip_steps=skip_steps)
                    except RollbackToCheckpoint:
                        # --nan-policy=restore: K consecutive bad steps.
                        # Reload the newest valid checkpoint and re-enter
                        # the loop at its exact step (the derived shuffle
                        # order makes the re-run deterministic). Bounded:
                        # persistent NaNs must eventually surface.
                        rollbacks += 1
                        if rollbacks > MAX_NAN_ROLLBACKS:
                            raise NonFiniteLossError(
                                f"nan-policy=restore: rolled back "
                                f"{MAX_NAN_ROLLBACKS} times and the run "
                                "still goes non-finite"
                            ) from None
                        epoch, skip_steps = self._rollback_to_checkpoint()
                        continue
                    skip_steps = 0  # only the resumed epoch is partial
                    # Fold in the epoch's own measurement (which already
                    # excludes the obs AOT compile) instead of re-timing
                    # around it — mean_step_ms must agree with the
                    # epoch/step_phases records of the same run.
                    timer.add(em["seconds"], em["steps"])
                    epoch_seconds.append(em["seconds"])
                    self.metrics.log("epoch", epoch=epoch,
                                     seconds=em["seconds"])

                    if cfg.eval_every and (epoch + 1) % cfg.eval_every == 0:
                        with span("eval", metrics=self.metrics.sink_or_none()):
                            ntests, ncorrect = self.evaluate()
                        result_acc = ncorrect / ntests
                        self.metrics.log("eval", epoch=epoch, ntests=ntests,
                                         ncorrect=ncorrect,
                                         accuracy=result_acc)
                    if cfg.checkpoint_dir and cfg.checkpoint_every and (
                        (epoch + 1) % cfg.checkpoint_every == 0
                    ):
                        with span("checkpoint", metrics=self.metrics.sink_or_none()):
                            self._ckpt.save(self.state, self._global_step())
                    epoch += 1

            if cfg.checkpoint_dir:
                with span("checkpoint", metrics=self.metrics.sink_or_none()):
                    self._ckpt.save(self.state, self._global_step())
        finally:
            # Drains the in-flight write even on an exceptional exit, so
            # its failure re-raises (chained) instead of dying with the
            # worker thread; on the normal path this is the usual close.
            if self._ckpt is not None:
                self._ckpt.close()
            # A fault that ABORTED the loop (injected crash) fired after
            # the last in-loop drain: flush its event here so the obs
            # stream records the fault in the attempt that hit it.
            self._drain_fault_events()
        if not (cfg.eval_every and cfg.epochs > start_epoch
                and cfg.epochs % cfg.eval_every == 0):
            ntests, ncorrect = self.evaluate()
            result_acc = ncorrect / ntests

        ntests = len(self.test_x)
        # The reference's one benchmark line (cnn.c:518).
        self.log.info("ntests=%d, ncorrect=%d", ntests, ncorrect)
        return TrainResult(
            epochs_run=cfg.epochs - start_epoch,
            final_step=self._global_step(),
            test_accuracy=result_acc,
            ntests=ntests,
            ncorrect=ncorrect,
            epoch_seconds=epoch_seconds,
            mean_step_ms=timer.mean_step_ms,
        )

    # ------------------------------------------------------------------

    def evaluate(self, params=None) -> tuple[int, int]:
        """Forward argmax sweep over the test set (cnn.c:494-518).
        Returns (ntests, ncorrect). Pads the tail batch; padding rows are
        excluded from the count."""
        if params is None:
            params = (
                self.state["flat_params"] if self.n_pipe > 1
                else self.state["params"]
            )
        n = len(self.test_x)
        b = self._eval_batch
        ncorrect = 0
        for start in range(0, n, b):
            chunk = self.test_x[start : start + b]
            valid = len(chunk)
            if valid < b:
                pad = np.zeros((b - valid, *chunk.shape[1:]), chunk.dtype)
                chunk = np.concatenate([chunk, pad])
            if self.n_pipe > 1:
                x_mb = jnp.asarray(chunk).reshape(
                    (self._pp_M, -1) + chunk.shape[1:]
                )
                logits = jax.device_get(
                    self.eval_step(params, pp_shard_batch(x_mb, self.mesh))
                ).reshape(b, -1)
            else:
                x = dp_shard_batch(jnp.asarray(chunk), self.mesh)
                logits = jax.device_get(self.eval_step(params, x))
            pred = np.argmax(logits[:valid], axis=-1)
            ncorrect += int((pred == self.test_labels[start : start + valid]).sum())
        return n, ncorrect
