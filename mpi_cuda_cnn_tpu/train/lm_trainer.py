"""End-to-end LM trainer: corpus -> trained TransformerLM.

The product form of the long-context path (train/lm.py has the step;
this has the loop): char-level corpus, random-window batches, train/eval
split, checkpointing, and the parallelism surface — a mesh with a 'data'
and/or 'seq' axis. With a 'seq' axis the step is the sequence-parallel
shard_map program (parallel/sp.py: ring / ring-flash / Ulysses
attention, MoE blocks expert-parallel over the same axis); without one
it is the plain jitted step (data-parallel via GSPMD from the batch
sharding). The CNN Trainer (train/trainer.py) is the reference-parity
loop; this is its twin for the model family the reference never had.
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import TransformerLM
from ..obs import cost as obs_cost
from ..obs.device import emit_step_telemetry
from ..obs.trace import span
from ..parallel.dp import replicate
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, make_mesh
from ..parallel.sp import SEQ_AXIS, make_sp_lm_train_step
from ..faults import (
    MAX_NAN_ROLLBACKS,
    NanGuard,
    NonFiniteLossError,
    PreemptionGuard,
    RollbackToCheckpoint,
    all_finite,
    drain_preemption,
    step_is_finite,
)
from ..obs.metrics import MetricsRegistry
from ..parallel.distributed import barrier, process_info
from ..utils.logging import MetricsLogger, get_logger
from ..utils.profiling import StepTimer
from ..utils.sync import hard_block
from .checkpoint import (
    AsyncCheckpointer,
    restore_latest,
    validate_resume_meta,
)
from .lm import get_attn_fn, lm_loss, make_lm_state, make_lm_train_step, pick_attn_impl
from .optimizer import make_optimizer


def load_corpus(spec: str, package_root: Path | None = None) -> np.ndarray:
    """Resolve a corpus spec to a uint8/int32 token array (char-level).

    "self"      — the framework's own Python sources (real text, zero
                  network: the analog of the digits dataset for the LM).
    "synthetic" — cyclic-successor tokens (deterministic, converges fast).
    a path      — any local text/bytes file.
    """
    if spec == "synthetic":
        return (np.arange(1 << 20) % 251).astype(np.int32)
    if spec == "self":
        root = package_root or Path(__file__).resolve().parents[1]
        parts = [p.read_bytes() for p in sorted(root.rglob("*.py"))]
        data = b"\n".join(parts)
    else:
        data = Path(spec).read_bytes()
    if len(data) < 1 << 12:
        raise ValueError(f"corpus {spec!r} too small: {len(data)} bytes")
    return np.frombuffer(data, np.uint8).astype(np.int32)


def _pick_ring_impl(seq_len: int, n_seq: int) -> str:
    """Shared auto rule for the sequence-parallel fold: the fused flash
    kernel on a real TPU with 128-aligned per-shard sequences (its block
    granularity), the plain jnp ring otherwise. One definition for the
    SP and TP x SP branches — the two must never drift."""
    on_tpu = jax.default_backend() == "tpu"
    return "ring_flash" if on_tpu and (seq_len // n_seq) % 128 == 0 \
        else "ring"


@dataclasses.dataclass
class LMResult:
    steps_run: int
    final_loss: float
    eval_loss: float
    eval_ppl: float
    tokens_per_s: float


class LMTrainer:
    """tokens (int32 stream) + config -> trained params.

    Batches are random (seq_len+1)-windows of the training stream; eval
    is mean NLL over deterministic windows of the held-out tail (10%).
    """

    def __init__(self, cfg, *, mesh=None,
                 metrics: MetricsLogger | None = None, faults=None,
                 preempt: PreemptionGuard | None = None, registry=None,
                 clock=None):
        self.cfg = cfg
        self.log = get_logger()
        self.metrics = metrics or MetricsLogger()
        # Runtime metrics registry (ISSUE 6) — same contract as the CNN
        # Trainer's: ONE shared registry across supervisor rebuilds
        # (restart/step totals survive), a private one standalone.
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        # `clock` has the time.perf_counter call shape and is the ONLY
        # time source the run loop and its telemetry fold read — a
        # FakeClock here makes step_ms/tokens_per_s registry values
        # deterministic (the PR-4 contract, same as StepTimer's).
        self._clock = clock if clock is not None else time.perf_counter
        # Fault hooks + NaN/Inf guard (ISSUE 4) — same contract as the
        # CNN Trainer: `faults` is a faults.FaultInjector shared across
        # supervisor restarts; the guard's policy rules are the shared
        # faults.NanGuard (one implementation for both trainers).
        self.faults = faults
        # Preemption guard (ISSUE 5) — same contract as the CNN
        # Trainer's: the CLI installs signal handlers and shares one;
        # the default answers injected `preempt` faults only.
        self._preempt = preempt if preempt is not None else PreemptionGuard()
        self._nan = NanGuard(getattr(cfg, "nan_policy", "off"),
                             getattr(cfg, "nan_max_bad", 3))
        self._finite_fn = jax.jit(all_finite) if self._nan.active else None

        tokens = load_corpus(cfg.corpus)
        vocab = int(tokens.max()) + 1
        split = max(len(tokens) - len(tokens) // 10, cfg.seq_len + 1)
        self.train_tokens = tokens[:split]
        self.eval_tokens = tokens[split:]
        if len(self.train_tokens) < cfg.seq_len + 1:
            raise ValueError(
                f"corpus ({len(tokens)} tokens) shorter than --seq-len "
                f"{cfg.seq_len}"
            )
        # Validate the post-training sample request NOW — its failure
        # after an hours-long run would lose the run's whole purpose.
        if cfg.sample_tokens < 0 or cfg.sample_tokens >= cfg.seq_len:
            raise ValueError(
                f"--sample-tokens {cfg.sample_tokens} must be in "
                f"[0, seq_len {cfg.seq_len}) — the prompt needs >= 1 "
                f"position of the decode budget"
            )
        if cfg.decode_cache_dtype not in ("float32", "bfloat16", "int8",
                                          "auto"):
            # Same rationale: the auto-generated flag parser is type=str,
            # so a typo ('bf16') would otherwise surface only at
            # sampling time, after the whole run. "auto" (VERDICT 7)
            # routes from the banked int8 table at sample time: int8
            # for GQA/MQA, bfloat16 for MHA (generate.pick_cache_dtype).
            raise ValueError(
                f"--decode-cache-dtype {cfg.decode_cache_dtype!r} must "
                "be 'float32', 'bfloat16', 'int8', or 'auto'"
            )
        if cfg.decode_weights_dtype not in ("float32", "bfloat16",
                                            "int8", "auto"):
            # Same early-validation contract as decode_cache_dtype: the
            # auto-generated parser is type=str, so a typo would
            # otherwise surface only at sampling time. "auto" routes
            # int8 for GQA/MQA, f32 for MHA (pick_weights_dtype — one
            # routing table with the cache's).
            raise ValueError(
                f"--decode-weights-dtype {cfg.decode_weights_dtype!r} "
                "must be 'float32', 'bfloat16', 'int8', or 'auto'"
            )
        if cfg.sample_top_k < 0 or not 0.0 <= cfg.sample_top_p <= 1.0:
            raise ValueError(
                f"--sample-top-k {cfg.sample_top_k} must be >= 0 and "
                f"--sample-top-p {cfg.sample_top_p} in [0, 1]"
            )
        if (cfg.sample_top_k or cfg.sample_top_p) and \
                cfg.sample_temperature <= 0:
            raise ValueError(
                "--sample-top-k/--sample-top-p restrict SAMPLING — set "
                "--sample-temperature > 0 (greedy already takes the "
                "single most likely token)"
            )
        if cfg.sample_speculative_k:
            if cfg.sample_speculative_k < 2:
                raise ValueError(
                    f"--sample-speculative-k {cfg.sample_speculative_k} "
                    "must be >= 2 (the verify block needs proposals)"
                )
            # --sample-temperature > 0 composes since round 5: the
            # speculative path rejection-samples, output law == plain
            # temperature sampling's (models/generate.py).
            if cfg.sample_tokens and cfg.sample_tokens + \
                    cfg.sample_speculative_k + 2 > cfg.seq_len:
                # The same fail-NOW rationale as the checks above: the
                # verify block needs k positions of cache slack beyond
                # prompt (>= 2) + tokens, and sample() runs after the
                # whole training run.
                raise ValueError(
                    f"--sample-tokens {cfg.sample_tokens} + speculative "
                    f"slack k={cfg.sample_speculative_k} + a >= 2-token "
                    f"prompt exceeds seq_len {cfg.seq_len}"
                )

        self.model = TransformerLM(
            vocab=vocab, dim=cfg.dim, heads=cfg.heads, depth=cfg.depth,
            max_seq=cfg.seq_len, moe_experts=cfg.moe_experts,
            moe_top_k=cfg.moe_top_k, kv_heads=cfg.kv_heads, pos=cfg.pos,
        )

        ndev = cfg.num_devices or len(jax.devices())
        if mesh is None:
            from ..utils.config import parse_mesh_shape

            axes = parse_mesh_shape(cfg.mesh_shape, ndev)
            mesh = make_mesh(axes, devices=jax.devices()[:ndev])
        self.mesh = mesh
        from ..parallel.ep import EXPERT_AXIS

        self.n_seq = self.mesh.shape.get(SEQ_AXIS, 1)
        self.n_data = self.mesh.shape.get(DATA_AXIS, 1)
        self.n_model = self.mesh.shape.get(MODEL_AXIS, 1)
        self.n_pipe = self.mesh.shape.get(PIPE_AXIS, 1)
        self.n_expert = self.mesh.shape.get(EXPERT_AXIS, 1)
        if self.n_expert > 1 and (self.n_seq > 1 or self.n_model > 1
                                  or self.n_pipe > 1 or cfg.fsdp):
            raise ValueError(
                "an 'expert' mesh axis composes with 'data' only "
                "(EP x DP, parallel/ep.py make_ep_lm_train_step); MoE "
                "under a 'seq' axis rides EP x SP instead — drop the "
                "other axes/--fsdp or the expert axis"
            )
        if cfg.batch_size % (self.n_data * self.n_expert):
            raise ValueError(
                f"batch_size {cfg.batch_size} not divisible by "
                f"data x expert shards ({self.n_data} x {self.n_expert})"
            )
        if cfg.moe_dispatch_chunk and (
            self.n_expert > 1 or self.n_seq > 1 or self.n_model > 1
            or self.n_pipe > 1
        ):
            raise ValueError(
                "--moe-dispatch-chunk is the SINGLE-DEVICE (or pure-DP) "
                "quadratic-dispatch lever; expert/seq/model/pipe meshes "
                "already shard the routed tokens — drop one of the two"
            )
        if cfg.moe_dispatch_chunk and not cfg.moe_experts:
            raise ValueError(
                "--moe-dispatch-chunk needs an MoE model (--moe-experts)"
            )
        if cfg.moe_dispatch_dtype:
            if not cfg.moe_experts:
                raise ValueError(
                    "--moe-dispatch-dtype needs an MoE model "
                    "(--moe-experts)"
                )
            if cfg.moe_dispatch_dtype not in ("bfloat16", "float32"):
                raise ValueError(
                    f"--moe-dispatch-dtype {cfg.moe_dispatch_dtype!r} "
                    "must be 'bfloat16' or 'float32'"
                )
            if self.n_expert > 1 or self.n_seq > 1 or self.n_pipe > 1:
                # Only the plain jitted step (data/model/FSDP GSPMD
                # meshes) threads the override; silently dropping it on
                # the shard_map paths would let a run believe bf16
                # dispatch was active while building f32 tensors —
                # reject, same policy as --moe-dispatch-chunk. (Under a
                # bf16 compute path those meshes already build bf16
                # dispatch: it follows x.dtype.)
                raise ValueError(
                    "--moe-dispatch-dtype rides the plain jitted step "
                    "(data/model/FSDP meshes); the expert/seq/pipe "
                    "shard_map steps don't thread it — drop one of the "
                    "two (bf16 compute already gives bf16 dispatch "
                    "there)"
                )
        if self.n_model > 1 and self.n_seq > 1:
            # TP x SP (parallel/tp_sp.py): Megatron inside the ring
            # shard_map. Structural checks (MoE, divisibility) fire at
            # state construction via _check_tp_sp.
            if cfg.fsdp:
                raise ValueError(
                    "--fsdp does not compose with the TP x SP shard_map "
                    "step; drop it or use data:N,model:M"
                )
            allowed = ("auto", "oracle", "ring", "ring_flash", "flash")
            if self.n_pipe == 1:
                allowed += ("ulysses",)  # pipelined stages: ring only
            if cfg.attn_impl not in allowed:
                raise ValueError(
                    f"--attn-impl {cfg.attn_impl!r} is not wired into "
                    "this mesh (TP x SP runs ring/ring_flash/ulysses on "
                    "the local heads; with a 'pipe' axis, ring/"
                    "ring_flash only); use auto"
                )
        if self.n_pipe > 1 and cfg.fsdp:
            raise ValueError(
                "the LM's 'pipe' axis composes with 'data', 'model', and "
                "'seq' (up to the full 4D pipe x model x seq x data mesh; "
                "parallel/pp_lm.py, tp_pp_lm.py) but not with --fsdp; "
                "drop the flag or the pipe axis"
            )
        if self.n_pipe > 1 and cfg.batch_size % (self.n_pipe * self.n_data):
            raise ValueError(
                f"batch_size {cfg.batch_size} not divisible by "
                f"num_microbatches x data-axis "
                f"({self.n_pipe} x {self.n_data})"
            )
        if self.n_pipe > 1 and self.n_seq == 1 and \
                cfg.attn_impl not in ("auto", "oracle", "flash"):
            raise ValueError(
                f"--attn-impl {cfg.attn_impl!r} needs a 'seq' mesh axis "
                "(ring attention shards positions); the pipelined stages "
                "see the full sequence — use auto, flash, or oracle"
            )
        if cfg.batch_size % self.n_data:
            raise ValueError(
                f"batch_size {cfg.batch_size} not divisible by data-axis "
                f"size {self.n_data}"
            )
        if cfg.grad_accum > 1:
            if self.n_pipe > 1 or (self.n_seq > 1 and self.n_model > 1):
                raise ValueError(
                    "--grad-accum is not wired into this mesh: the "
                    "'pipe' axis already accumulates over "
                    "--num-microbatches, and the TP x SP step doesn't "
                    "chunk — drop the flag or those axes (plain/TP/"
                    "FSDP/SP/EP meshes all accept it)"
                )
            per_shard = cfg.batch_size // (self.n_data * self.n_expert)
            if per_shard % cfg.grad_accum:
                raise ValueError(
                    f"per-shard batch {per_shard} not divisible by "
                    f"grad_accum {cfg.grad_accum}"
                )
        if cfg.seq_len % self.n_seq:
            raise ValueError(
                f"seq_len {cfg.seq_len} not divisible by seq-axis size "
                f"{self.n_seq}"
            )
        if cfg.fsdp and self.n_data <= 1:
            # Structural mesh check belongs here, before any
            # step/optimizer construction — the user should see the mesh
            # error first. (fsdp + 'seq' composes: ZeRO x ring inside
            # the SP shard_map, parallel/sp.py state_specs.)
            raise ValueError(
                "--fsdp needs a 'data' mesh axis of size > 1 "
                f"(mesh_shape={cfg.mesh_shape!r})"
            )
        if cfg.elastic_width:
            # Elastic (width-invariant) training rides the pure-DP
            # shard_map step only — sharded-param layouts change WHAT
            # is reduced when the width changes, and the dispatch-dtype
            # knobs aren't threaded through the elastic body.
            from ..parallel.elastic import check_elastic_width

            if (self.n_seq > 1 or self.n_model > 1 or self.n_pipe > 1
                    or self.n_expert > 1 or cfg.fsdp):
                raise ValueError(
                    "--elastic-width needs a pure data-parallel mesh "
                    f"(mesh_shape={cfg.mesh_shape!r}/--fsdp shard the "
                    "state; cross-width bitwise resume is only defined "
                    "for replicated params)"
                )
            if cfg.grad_accum > 1:
                raise ValueError(
                    "--elastic-width already scans canonical "
                    "microbatches; --grad-accum is redundant with it"
                )
            if cfg.moe_dispatch_chunk or cfg.moe_dispatch_dtype:
                raise ValueError(
                    "--moe-dispatch-chunk/--moe-dispatch-dtype ride the "
                    "plain jitted step; the elastic shard_map step does "
                    "not thread them — drop one of the two"
                )
            check_elastic_width(cfg.elastic_width, cfg.batch_size,
                                self.n_data)

        # Cosine needs positive decay_steps: clamp warmup only when it
        # would swallow the whole (short) run, and say so.
        warmup = cfg.warmup_steps
        if warmup >= cfg.steps:
            warmup = max(cfg.steps - 1, 0)
            self.log.warning(
                "warmup_steps %d >= steps %d; clamped to %d",
                cfg.warmup_steps, cfg.steps, warmup,
            )
        # The pipelined, Megatron x ring, and ZeRO x ring steps clip
        # IN-STEP with a cross-rank-correct global norm (their params
        # are sharded, so optax's per-rank clip_by_global_norm would
        # compute a partial norm); everywhere else the optax transform
        # does it.
        clip_in_step = self.n_pipe > 1 or self.n_seq > 1 and (
            self.n_model > 1 or cfg.fsdp
        )
        self.optimizer = make_optimizer(
            cfg.lr, opt="adamw", schedule=cfg.lr_schedule,
            total_steps=cfg.steps or None, warmup_steps=warmup,
            weight_decay=cfg.weight_decay,
            grad_clip=0.0 if clip_in_step else cfg.grad_clip,
        )
        compute_dtype = (
            jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else None
        )
        self._compute_dtype = compute_dtype

        if cfg.ce_chunk and (cfg.seq_len // self.n_seq) % cfg.ce_chunk:
            raise ValueError(
                f"--ce-chunk {cfg.ce_chunk} must divide the per-shard "
                f"sequence {cfg.seq_len // self.n_seq} (seq_len "
                f"{cfg.seq_len} over seq:{self.n_seq})"
            )
        if self.n_pipe > 1:
            # GPipe over stacked transformer blocks (parallel/pp_lm.py):
            # blocks stage-sharded over 'pipe', microbatches over 'data'.
            from ..parallel.pp_lm import (
                make_pp_lm_state,
                make_pp_lm_train_step,
            )

            params = self.model.init(jax.random.key(cfg.seed))
            if self.n_seq > 1:
                # SP x PP (x DP): long sequences THROUGH a pipelined
                # model — ring attention inside each GPipe stage; with a
                # 'model' axis too, the FULL 4D mesh (Megatron blocks,
                # ring on the local heads).
                impl = cfg.attn_impl
                if impl in ("auto", "flash"):
                    impl = _pick_ring_impl(cfg.seq_len, self.n_seq)
                elif impl == "oracle":
                    impl = "ring"
                self.attn_impl = impl
                if self.n_model > 1:
                    from ..parallel.tp_pp_lm import (
                        make_tp_pp_lm_state,
                        make_tp_pp_lm_train_step,
                    )

                    self.state = make_tp_pp_lm_state(
                        self.model, params, self.optimizer, self.mesh
                    )
                    self.train_step = make_tp_pp_lm_train_step(
                        self.model, self.optimizer, self.mesh, self.state,
                        compute_dtype=compute_dtype, remat=cfg.remat,
                        grad_clip=cfg.grad_clip, attn_impl=impl,
                        ce_chunk=cfg.ce_chunk, donate=cfg.donate,
                    )
                else:
                    from ..parallel.pp_lm import make_sp_pp_lm_train_step

                    self.state = make_pp_lm_state(
                        self.model, params, self.optimizer, self.mesh
                    )
                    self.train_step = make_sp_pp_lm_train_step(
                        self.model, self.optimizer, self.mesh, self.state,
                        compute_dtype=compute_dtype, remat=cfg.remat,
                        grad_clip=cfg.grad_clip, impl=impl,
                        ce_chunk=cfg.ce_chunk, donate=cfg.donate,
                    )
            else:
                # Each stage sees the full sequence, so the plain
                # attention router applies unchanged — flash per stage
                # on TPU.
                self.attn_impl = pick_attn_impl(
                    cfg.attn_impl, cfg.seq_len, compute_dtype
                )
                if self.n_model > 1:
                    # TP x PP (x DP): Megatron inside the GPipe stages —
                    # the 3D layout (parallel/tp_pp_lm.py).
                    from ..parallel.tp_pp_lm import (
                        make_tp_pp_lm_state as make_state,
                        make_tp_pp_lm_train_step as make_step,
                    )
                else:
                    make_state, make_step = make_pp_lm_state, \
                        make_pp_lm_train_step
                self.state = make_state(
                    self.model, params, self.optimizer, self.mesh
                )
                self.train_step = make_step(
                    self.model, self.optimizer, self.mesh, self.state,
                    compute_dtype=compute_dtype, remat=cfg.remat,
                    grad_clip=cfg.grad_clip, attn_impl=self.attn_impl,
                    ce_chunk=cfg.ce_chunk, donate=cfg.donate,
                )
        elif self.n_seq > 1 and self.n_model > 1:
            from ..parallel.tp_sp import (
                make_tp_sp_lm_train_step,
                make_tp_sp_state,
            )

            # Honor an explicit choice; "auto"/"flash" use the shared
            # rule, "oracle" maps to the exact jnp ring.
            impl = cfg.attn_impl
            if impl in ("auto", "flash"):
                impl = _pick_ring_impl(cfg.seq_len, self.n_seq)
            elif impl == "oracle":
                impl = "ring"
            self.attn_impl = impl
            params = self.model.init(jax.random.key(cfg.seed))
            self.state, specs = make_tp_sp_state(
                self.model, params, self.optimizer, self.mesh
            )
            self.train_step = make_tp_sp_lm_train_step(
                self.model, self.optimizer, self.mesh, specs,
                data_axis=DATA_AXIS if self.n_data > 1 else None,
                compute_dtype=compute_dtype, remat=cfg.remat,
                ce_chunk=cfg.ce_chunk, impl=self.attn_impl,
                grad_clip=cfg.grad_clip, donate=cfg.donate,
            )
        elif self.n_expert > 1:
            # EP x DP: batch sharded over (data, expert) jointly; the
            # MoE dispatch all_to_alls over 'expert' inside the step.
            from ..parallel.ep import make_ep_lm_train_step

            self.attn_impl = pick_attn_impl(
                cfg.attn_impl, cfg.seq_len, compute_dtype
            )
            self.train_step = make_ep_lm_train_step(
                self.model, self.optimizer, self.mesh,
                data_axis=DATA_AXIS if self.n_data > 1 else None,
                attn_impl=self.attn_impl, remat=cfg.remat,
                compute_dtype=compute_dtype, ce_chunk=cfg.ce_chunk,
                grad_accum=cfg.grad_accum, donate=cfg.donate,
            )
        elif self.n_seq > 1:
            impl = cfg.attn_impl
            if impl in ("auto", "flash"):
                impl = _pick_ring_impl(cfg.seq_len, self.n_seq)
            elif impl == "oracle":
                impl = "ring"
            self.attn_impl = impl
            sp_specs = None
            if cfg.fsdp:
                # ZeRO x ring: state placed by the generic FSDP specs
                # (largest dim over 'data'); the step consumes the
                # placement's own spec tree, so the two cannot disagree.
                from ..parallel.fsdp import make_fsdp_state, state_specs

                params = self.model.init(jax.random.key(cfg.seed))
                self.state = make_fsdp_state(
                    params, self.optimizer, self.mesh
                )
                sp_specs = state_specs(self.state)
            self.train_step = make_sp_lm_train_step(
                self.model, self.optimizer, self.mesh, impl=impl,
                data_axis=DATA_AXIS if self.n_data > 1 else None,
                remat=cfg.remat, compute_dtype=compute_dtype,
                ce_chunk=cfg.ce_chunk, state_specs=sp_specs,
                grad_clip=cfg.grad_clip if cfg.fsdp else 0.0,
                grad_accum=cfg.grad_accum, donate=cfg.donate,
            )
        elif cfg.elastic_width:
            # Width-invariant canonical-tree DP (ISSUE 5): the explicit
            # shard_map step whose trajectory is bitwise identical on
            # any supported data width — what makes a preempted run
            # resumable on a different topology (train/lm.py).
            from .lm import make_elastic_lm_train_step

            self.train_step, self.attn_impl = make_elastic_lm_train_step(
                self.model, self.optimizer, self.mesh,
                elastic_width=cfg.elastic_width, attn_impl=cfg.attn_impl,
                seq_len=cfg.seq_len, compute_dtype=compute_dtype,
                remat=cfg.remat, ce_chunk=cfg.ce_chunk,
                donate=cfg.donate,
            )
        else:
            self.attn_impl = pick_attn_impl(
                cfg.attn_impl, cfg.seq_len, compute_dtype
            )
            self.train_step = make_lm_train_step(
                self.model, self.optimizer, attn_impl=self.attn_impl,
                seq_len=cfg.seq_len, compute_dtype=compute_dtype,
                remat=cfg.remat, ce_chunk=cfg.ce_chunk,
                grad_accum=cfg.grad_accum,
                moe_dispatch_chunk=cfg.moe_dispatch_chunk,
                moe_dispatch_dtype=(
                    jnp.dtype(cfg.moe_dispatch_dtype)
                    if cfg.moe_dispatch_dtype else None
                ),
                donate=cfg.donate,
            )
        if self.n_pipe > 1 or self.n_seq > 1 and (self.n_model > 1
                                                  or cfg.fsdp):
            pass  # state already built above (PP / TP x SP / FSDP x SP)
        elif cfg.fsdp:
            # ZeRO-style sharding for the LM — the same generic spec
            # machinery as the CNN path (parallel/fsdp.py); with a
            # 'model' axis present the TP specs are the base and 'data'
            # takes the largest remaining dim (FSDP x TP). Mesh shape
            # was validated up front with the other structural checks.
            from ..parallel.fsdp import make_fsdp_state

            base = None
            if self.n_model > 1:
                from ..parallel.tp import lm_tp_specs

                base = lm_tp_specs(self.model, self.mesh)
            params = self.model.init(jax.random.key(cfg.seed))
            self.state = make_fsdp_state(
                params, self.optimizer, self.mesh, base_specs=base
            )
        elif self.n_model > 1:
            # Megatron-style TP as GSPMD placement (parallel/tp.py
            # lm_tp_specs): the SAME plain jitted step, params sharded
            # over 'model' — XLA inserts the collectives.
            from ..parallel.tp import make_lm_tp_state

            params = self.model.init(jax.random.key(cfg.seed))
            self.state = make_lm_tp_state(
                self.model, params, self.optimizer, self.mesh
            )
        else:
            self.state = replicate(
                make_lm_state(self.model, self.optimizer, cfg.seed),
                self.mesh,
            )
        self._eval_fn = None
        # Checkpoint topology metadata + multihost write discipline —
        # same scheme as the CNN Trainer (ISSUE 5): manifest records
        # the mesh/elastic width per checkpoint, process 0 is the only
        # writer, a barrier fences publication.
        from ..parallel.mesh import describe_mesh

        self._proc = process_info()
        self._ckpt_meta = {
            "mesh": describe_mesh(self.mesh),
            "elastic_width": cfg.elastic_width,
            "process_count": self._proc.process_count,
        }
        self._ckpt = (
            AsyncCheckpointer(cfg.checkpoint_dir,
                              async_=cfg.async_checkpoint, faults=faults,
                              meta=self._ckpt_meta, process=self._proc,
                              barrier=barrier)
            if cfg.checkpoint_dir else None
        )

    # ------------------------------------------------------------------

    def _sample_batch(self, step: int):
        """(B, S) inputs + targets: random windows of the train stream.

        The RNG is derived from (seed, step), not a stream advanced from
        cfg.seed, so a run resumed at step k sees exactly the windows the
        uninterrupted run would have seen at steps k, k+1, ... — the same
        step-exact-resume contract the CNN trainer keeps with its
        (seed, epoch)-derived shuffle order.
        """
        cfg = self.cfg
        # A window consumes seq_len+1 tokens; valid starts are
        # [0, len - seq_len - 1] inclusive, so the exclusive high bound is
        # len - seq_len (== 1 for the minimal corpus the ctor accepts).
        n = len(self.train_tokens) - cfg.seq_len
        rng = np.random.default_rng((cfg.seed, step))
        starts = rng.integers(0, n, size=cfg.batch_size)
        idx = starts[:, None] + np.arange(cfg.seq_len + 1)[None, :]
        w = self.train_tokens[idx]
        return jnp.asarray(w[:, :-1]), jnp.asarray(w[:, 1:])

    def _place(self, t):
        """Shard (B, S) over (data, seq) mesh axes — or microbatch to
        (M, mb, S) with mb over 'data' on the pipelined mesh."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.n_pipe > 1:
            from ..parallel.pp_lm import (
                pp_lm_shard_batch,
                sp_pp_shard_batch,
            )

            t = t.reshape((self.n_pipe, -1) + t.shape[1:])
            place = (sp_pp_shard_batch if self.n_seq > 1
                     else pp_lm_shard_batch)
            return place(t, self.mesh)
        from ..parallel.ep import EXPERT_AXIS

        batch_axes = tuple(
            a for a, n in ((DATA_AXIS, self.n_data),
                           (EXPERT_AXIS, self.n_expert)) if n > 1
        )
        spec = P(
            batch_axes if len(batch_axes) > 1
            else (batch_axes[0] if batch_axes else None),
            SEQ_AXIS if self.n_seq > 1 else None,
        )
        return jax.device_put(t, NamedSharding(self.mesh, spec))

    def _standard_layout(self) -> bool:
        """True when the live state's params are already the standard
        tree (DP / TP / FSDP / SP placements) — eval and decode can run
        straight off the placement, GSPMD partitioning them; the packed
        (PP) and head-structured (TP x SP) layouts need _host_params."""
        p = self.state["params"]
        return "rest" not in p and not (
            p["blocks"] and p["blocks"][0]["wo"].ndim == 3
        )

    def _host_params(self):
        """Host copy of the params in the STANDARD tree layout: the
        pipelined state stores stacked blocks (unstack), the TP x SP
        state stores head-structured weights (un-reshape) — eval and
        decode consume the standard tree either way."""
        p = jax.device_get(self.state["params"])
        if "rest" in p:
            # Stacked wo is (L, h*hd, d); the TP x PP packed layout is
            # additionally head-structured: (L, H, hd, d).
            if p["blocks"]["wo"].ndim == 4:
                from ..parallel.tp_pp_lm import unstack_tp_blocks

                p = unstack_tp_blocks(p, self.model)
            else:
                from ..parallel.pp_lm import unstack_blocks

                p = unstack_blocks(p, self.model.depth)
        elif p["blocks"] and p["blocks"][0]["wo"].ndim == 3:
            from ..parallel.tp_sp import from_tp_layout

            p = from_tp_layout(p, self.model)
        return p

    def _place_host_state(self, host_state) -> None:
        """Install a host-side state pytree with the live shardings."""
        shardings = jax.tree.map(lambda a: a.sharding, self.state)
        self.state = jax.device_put(host_state, shardings)

    def _drop_bad_update(self, step: int, snap) -> None:
        """Apply --nan-policy to a non-finite step (faults.NanGuard owns
        the rules; abort and rollback raise there). A plain skip drops
        the update by reinstalling the pre-step snapshot with the step
        counter ADVANCED past the dropped batch — state["step"] must
        equal batches consumed, or a crash-restart would resume short by
        the skipped steps (see Trainer._drop_bad_update)."""
        self._nan.bad_step(step, logger=self.log, metrics=self.metrics)
        snap = dict(snap)
        snap["step"] = np.asarray(snap["step"]) + 1
        self._place_host_state(snap)

    def _rollback_to_checkpoint(self) -> int:
        """Reload the newest valid checkpoint after a nan-policy=restore
        rollback; returns the step to re-enter at."""
        if self._ckpt is not None:
            self._ckpt.wait()  # the in-flight write may be newest
        restored, path = restore_latest(
            self.cfg.checkpoint_dir, jax.device_get(self.state),
            logger=self.log, metrics=self.metrics,
        ) if self.cfg.checkpoint_dir else (None, None)
        if restored is None:
            raise NonFiniteLossError(
                "nan-policy=restore: no valid checkpoint to roll "
                "back to (set --checkpoint-dir/--checkpoint-every)"
            )
        self._place_host_state(restored)
        self._nan.step_ok()
        step0 = int(jax.device_get(self.state["step"]))
        self.metrics.log("fault", kind="nan_restore", step=step0,
                         path=path.name)
        self.log.warning("nan-policy=restore: rolled back to %s (step %d)",
                         path, step0)
        return step0

    def _step_boundary(self, global_step: int) -> None:
        """Per-step fault/preemption hook (the CNN Trainer's twin): an
        injected ``preempt`` fault sets the same flag a real SIGTERM
        would; a pending preemption then drains the shared orderly exit
        (faults.drain_preemption)."""
        if self.faults is not None:
            for f in self.faults.fire("train.step", global_step):
                if f.kind == "preempt":
                    self._preempt.request()
            for ev in self.faults.drain_events():
                self.metrics.log("fault", **ev)
        drain_preemption(self._preempt, state=self.state,
                         global_step=global_step, ckpt=self._ckpt,
                         metrics=self.metrics, logger=self.log)

    def train(self) -> LMResult:
        cfg = self.cfg
        start_step = 0
        if cfg.resume and cfg.checkpoint_dir:
            host = jax.device_get(self.state)
            # restore_latest verifies manifest checksums and falls back
            # past corrupt files to the newest valid checkpoint.
            restored, ckpt = restore_latest(cfg.checkpoint_dir, host,
                                            logger=self.log,
                                            metrics=self.metrics)
            if restored is not None:
                validate_resume_meta(ckpt, mesh=self.mesh,
                                     elastic_width=cfg.elastic_width,
                                     metrics=self.metrics, logger=self.log)
                shardings = jax.tree.map(lambda a: a.sharding, self.state)
                self.state = jax.device_put(restored, shardings)
                # The resumed-from checkpoint must survive later prunes
                # — it is the only valid restore point until the next
                # save lands.
                if self._ckpt is not None:
                    self._ckpt.protect = ckpt.name
                start_step = int(jax.device_get(self.state["step"]))
                self.metrics.log("ckpt", step=start_step, reason="resume",
                                 path=ckpt.name)
                self.log.info("resumed from %s at step %d", ckpt, start_step)
                # A checkpoint past --steps means nothing left to run; the
                # loop below is empty and steps_run clamps to 0.
                start_step = min(start_step, cfg.steps)

        t0 = self._clock()
        loss = float("nan")
        m = None
        timer = StepTimer(clock=self._clock)
        timer.start()
        logged_cost = False
        rollbacks = 0
        # Per-interval registry anchors (ISSUE 6): each log interval
        # folds its step-time mean and tokens/s into the runtime
        # registry, excluding the one-off obs AOT compile the timer
        # already excludes from its own envelope.
        last_t, last_step, last_exc = t0, start_step, 0.0
        try:
            step = start_step
            while step < cfg.steps:
                with timer.phase("data"):
                    tokens, targets = self._sample_batch(step)
                    tokens, targets = self._place(tokens), self._place(targets)
                if not logged_cost and self.metrics.jsonl_enabled:
                    logged_cost = True
                    # exclude(): the analysis costs an AOT compile that
                    # must not land in the step-phase attribution.
                    with timer.exclude():
                        if not obs_cost.log_program(
                            self.metrics, "lm_train_step", self.train_step,
                            self.state, tokens, targets,
                            compute_dtype=cfg.compute_dtype,
                        ):
                            self.log.warning(
                                "obs: cost analysis unavailable for "
                                "lm_train_step"
                            )
                # skip/restore must drop the bad update — hold the
                # pre-step state on host (donation consumes the buffers).
                snap = (jax.device_get(self.state)
                        if self._nan.snapshots else None)
                with timer.phase("dispatch"):
                    self.state, m = self.train_step(self.state, tokens, targets)
                try:
                    if self._nan.active and not step_is_finite(
                        m, self._finite_fn, self.state
                    ):
                        # Drop the update (abort/rollback raise); the
                        # checkpoint + crash hooks below still run — a
                        # skipped step consumed its batch, and a planned
                        # fault at this step value must not evaporate.
                        self._drop_bad_update(step, snap)
                    else:
                        self._nan.step_ok()
                        if cfg.log_every and (step + 1) % cfg.log_every == 0:
                            with timer.phase("device"):
                                loss = float(m["loss"])
                            self.metrics.log("train", step=step + 1,
                                             loss=loss)
                            now = self._clock()
                            n = step + 1 - last_step
                            dt = (now - last_t
                                  - (timer.excluded_s - last_exc))
                            if n > 0 and dt > 0:
                                reg = self.registry
                                reg.inc("train.steps", n)
                                reg.inc("train.heartbeats")
                                reg.observe("train.step_ms", 1e3 * dt / n)
                                reg.set(
                                    "train.tokens_per_s",
                                    n * cfg.batch_size * cfg.seq_len / dt,
                                )
                                # Loss gauge (ISSUE 8): health/top read
                                # it off `metrics` snapshots with its
                                # min/max envelope.
                                reg.set("train.loss", loss)
                                reg.emit(self.metrics, step=step + 1)
                            last_t, last_step = now, step + 1
                            last_exc = timer.excluded_s
                except RollbackToCheckpoint:
                    rollbacks += 1
                    if rollbacks > MAX_NAN_ROLLBACKS:
                        raise NonFiniteLossError(
                            f"nan-policy=restore: rolled back "
                            f"{MAX_NAN_ROLLBACKS} times and the run "
                            "still goes non-finite"
                        ) from None
                    step = self._rollback_to_checkpoint()
                    continue
                if cfg.checkpoint_dir and cfg.checkpoint_every and (
                    (step + 1) % cfg.checkpoint_every == 0
                ):
                    with timer.phase("checkpoint"):
                        self._ckpt.save(self.state, step + 1)
                self._step_boundary(step + 1)
                step += 1
            with timer.phase("device"):
                hard_block(self.state)
            # Exclude the obs AOT compile from the headline tokens/s —
            # telemetry must not sink the number it reports.
            dt = self._clock() - t0 - timer.excluded_s
            if cfg.checkpoint_dir:
                self._ckpt.save(self.state, cfg.steps)
        finally:
            # Even on an exceptional exit the in-flight write drains and
            # its failure re-raises (chained) — it cannot be dropped.
            if self._ckpt is not None:
                self._ckpt.close()
            # Flush fault events fired after the last in-loop drain
            # (e.g. the injected crash that aborted this attempt).
            if self.faults is not None:
                for ev in self.faults.drain_events():
                    self.metrics.log("fault", **ev)
        steps_run = cfg.steps - start_step
        loss = float(m["loss"]) if m is not None else loss
        timer.stop(max(steps_run, 1))
        emit_step_telemetry(self.metrics, timer, steps_run,
                            devices=list(self.mesh.devices.flat))
        if steps_run > 0:
            # Final registry snapshot: the headline tokens/s (same dt
            # the LMResult reports) plus any tail steps the log-interval
            # anchors missed.
            reg = self.registry
            if cfg.steps > last_step:
                reg.inc("train.steps", cfg.steps - last_step)
            reg.set("train.tokens_per_s",
                    steps_run * cfg.batch_size * cfg.seq_len
                    / max(dt, 1e-9))
            reg.emit(self.metrics, final=True)

        with span("eval", metrics=self.metrics.sink_or_none()):
            eval_loss = self.evaluate()
        tok_s = steps_run * cfg.batch_size * cfg.seq_len / max(dt, 1e-9)
        self.log.info(
            "lm done: steps=%d loss=%.4f eval_loss=%.4f ppl=%.2f tok/s=%.0f",
            steps_run, loss, eval_loss, float(np.exp(eval_loss)), tok_s,
        )
        return LMResult(
            steps_run=steps_run,
            final_loss=loss,
            eval_loss=eval_loss,
            eval_ppl=float(np.exp(eval_loss)),
            tokens_per_s=tok_s,
        )

    # ------------------------------------------------------------------

    def sample(self, num_tokens: int, *, prompt_len: int | None = None,
               temperature: float = 0.0, seed: int = 0):
        """Generate a continuation of the held-out stream with the
        KV-cache decode path (models/generate.py) — the product surface
        of inference: prompt from the eval tail, greedy by default.

        Returns (prompt, continuation) as int32 numpy arrays; the CLI
        decodes them as bytes for char-level corpora.
        """
        from ..models.generate import generate

        cfg = self.cfg
        # Speculative decoding needs k positions of cache slack beyond
        # prompt + num_tokens (the verify block may overshoot); shrink
        # the prompt, not k.
        spec_k = cfg.sample_speculative_k
        max_prompt = cfg.seq_len - num_tokens - spec_k
        if max_prompt < (2 if spec_k else 1):
            raise ValueError(
                f"--sample-tokens {num_tokens}"
                + (f" + speculative slack k={spec_k}" if spec_k else "")
                + f" leaves no room for a prompt within seq_len "
                f"{cfg.seq_len}"
            )
        p = min(prompt_len or max(cfg.seq_len // 2, 1), max_prompt)
        stream = (
            self.eval_tokens if len(self.eval_tokens) >= p
            else self.train_tokens
        )
        prompt = jnp.asarray(np.asarray(stream[:p])[None, :], jnp.int32)
        if self._standard_layout():
            # Decode STRAIGHT off the live placement — GSPMD partitions
            # the scan from it (sharded serving), no host round-trip.
            params = self.state["params"]
        else:
            # Packed (PP) / head-structured (TP x SP) layouts: convert
            # on host, then re-place with the Megatron TP shardings when
            # the mesh has a model axis (KV cache head-sharded).
            params = self._host_params()
            if self.n_model > 1:
                from ..parallel.tp import shard_lm_params

                params = shard_lm_params(self.model, params, self.mesh)
        wdt = self._weights_dtype()
        if wdt != "float32":
            # One-time serving-weights conversion (ISSUE 12): int8
            # per-channel QuantW / bf16 cast through the SAME forward
            # (qmatmul dispatch). Single-placement paths only — the
            # QuantW leaves don't carry Megatron shardings, and a
            # sample-time lever must not silently unshard the decode.
            if self.n_model > 1:
                raise ValueError(
                    "--decode-weights-dtype requires an unsharded "
                    "sample path (model-parallel decode keeps f32 "
                    "weights; set --decode-weights-dtype float32)"
                )
            from ..ops.pallas_gemv import quantize_decode_params

            params = quantize_decode_params(params, wdt)
        if cfg.sample_speculative_k:
            # Draft-free prompt-lookup speculation. Greedy at
            # temperature 0 (bitwise-exact contract); temperature > 0
            # runs rejection sampling — output law == plain sampling's
            # (models/generate.py _spec_sample_rows).
            if p < 2:
                # The lookup ngram (default 2) needs that much prompt;
                # fail here with the config's vocabulary rather than
                # deeper with the generator's (ADVICE round-4 finding).
                raise ValueError(
                    f"--sample-speculative-k needs a prompt of >= 2 "
                    f"tokens (resolved prompt length {p}; raise "
                    f"prompt_len or seq_len)"
                )
            from ..models.generate import lookup_speculative_generate

            toks = lookup_speculative_generate(
                self.model, params, prompt, num_tokens,
                k=cfg.sample_speculative_k,
                cache_dtype=self._cache_dtype(),
                temperature=temperature,
                key=jax.random.key(seed) if temperature > 0 else None,
                top_k=cfg.sample_top_k, top_p=cfg.sample_top_p,
            )
        else:
            toks = generate(
                self.model, params, prompt, num_tokens,
                temperature=temperature,
                key=jax.random.key(seed) if temperature > 0 else None,
                cache_dtype=self._cache_dtype(),
                top_k=cfg.sample_top_k, top_p=cfg.sample_top_p,
            )
        return np.asarray(prompt[0]), np.asarray(toks[0])

    def _cache_dtype(self) -> str:
        """--decode-cache-dtype with "auto" resolved against THIS
        model's head geometry (generate.pick_cache_dtype, VERDICT 7)."""
        from ..models.generate import pick_cache_dtype

        return pick_cache_dtype(self.cfg.decode_cache_dtype,
                                heads=self.model.heads,
                                kv_heads=self.model.n_kv)

    def _weights_dtype(self) -> str:
        """--decode-weights-dtype with "auto" resolved against THIS
        model's head geometry (generate.pick_weights_dtype — one
        routing table with the cache's)."""
        from ..models.generate import pick_weights_dtype

        return pick_weights_dtype(self.cfg.decode_weights_dtype,
                                  heads=self.model.heads,
                                  kv_heads=self.model.n_kv)

    def evaluate(self) -> float:
        """Mean next-token NLL over deterministic windows of the held-out
        tail. Standard-layout states feed the LIVE placement into the
        jitted forward (GSPMD partitions it — DP/TP/FSDP/SP); packed and
        head-structured states convert on host first (eval is tiny next
        to training either way)."""
        cfg = self.cfg
        s = cfg.seq_len
        stream = self.eval_tokens
        if len(stream) < s + 1:
            stream = self.train_tokens  # tiny-corpus fallback
        nwin = min(8, (len(stream) - 1) // s)
        if self._eval_fn is None:
            attn_fn = get_attn_fn(
                "flash" if self.attn_impl in ("flash", "ring_flash")
                else "oracle"
            )

            @jax.jit
            def eval_fn(params, tokens, targets):
                # ce_chunk rides along: the batched windows would
                # otherwise materialize (nwin, S, V) f32 logits on
                # exactly the configs the flag exists for.
                return lm_loss(
                    self.model, params, tokens, targets, attn_fn=attn_fn,
                    compute_dtype=self._compute_dtype, moe_aux_weight=0.0,
                    ce_chunk=self.cfg.ce_chunk,
                )

            self._eval_fn = eval_fn
        params = (
            self.state["params"] if self._standard_layout()
            else self._host_params()
        )
        if nwin == 0:
            return float("nan")
        # ONE batched forward over all windows (equal sizes make the
        # batch-mean NLL the mean of per-window means) instead of a
        # dispatch per window — 8x fewer host round-trips through the
        # tunnel, and the eval_fn jit cache sees one shape.
        wins = np.stack([
            np.asarray(stream[i * s : i * s + s + 1]) for i in range(nwin)
        ])
        return float(self._eval_fn(
            params, jnp.asarray(wins[:, :-1]), jnp.asarray(wins[:, 1:])
        ))
