"""Checkpoint / resume.

The reference has no serialization of any kind — weights live and die in
process memory, training always restarts from random init (SURVEY.md §5.4).
This module provides the missing capability as flat `.npz` archives: the
state pytree is flattened with `jax.tree_util` key paths as array names, so
checkpoints are a stable, inspectable format independent of Python pickling
(and of this framework — `np.load` reads them anywhere).

Crash safety (ISSUE 4): every file lands via tmp-write + atomic rename
(the npz AND the manifest — a crash mid-write can poison neither), the
manifest records a per-array crc32 for each live checkpoint,
`restore_checkpoint` verifies those checksums (raising
CheckpointCorruptError on mismatch), and `restore_latest` walks the
checkpoint list newest-first, falling back past corrupt or truncated
files to the newest one that verifies. A missing or unparsable manifest
degrades to the `ckpt_*.npz` glob with verification skipped — an old or
half-written manifest can never block a restore.
"""

from __future__ import annotations

import json
import re
import zlib
from pathlib import Path

import jax
import numpy as np

_STEP_RE = re.compile(r"ckpt_(\d+)\.npz$")

MANIFEST = "manifest.json"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint's bytes do not match its manifest checksums."""


def _flatten(state) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _checksum(arr: np.ndarray) -> str:
    """crc32 over the array bytes (+dtype/shape so a reinterpretation
    can't collide). Fast enough to run on every save at LM scale —
    integrity, not cryptography."""
    crc = zlib.crc32(np.ascontiguousarray(arr).tobytes())
    crc = zlib.crc32(f"{arr.dtype}:{arr.shape}".encode(), crc)
    return f"{crc:08x}"


def _load_manifest(ckpt_dir: Path) -> dict | None:
    """The directory manifest, or None when missing/unparsable — restore
    falls back to the ckpt_*.npz glob either way (ISSUE 4 satellite)."""
    path = ckpt_dir / MANIFEST
    try:
        mf = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None
    return mf if isinstance(mf, dict) else None


def _atomic_write_text(path: Path, text: str) -> None:
    tmp = path.parent / f".{path.name}.tmp"
    tmp.write_text(text)
    tmp.rename(path)


def save_checkpoint(ckpt_dir: str | Path, state, step: int, *, keep: int = 3,
                    faults=None, meta: dict | None = None,
                    protect: str | None = None, process=None,
                    barrier=None) -> Path:
    """Write state as ckpt_{step}.npz + the JSON manifest; prune old.

    Both files are tmp-written then renamed: a crash at ANY point leaves
    either the previous consistent (files, manifest) pair or the new
    one, never a torn file under a live name — and pruning runs only
    AFTER the new checkpoint's rename, so the window where the
    directory holds fewer than `keep` restorable checkpoints never
    opens (ISSUE 5 satellite). `faults` is a faults.FaultInjector hook
    (sites "ckpt.pre_rename" — between the npz tmp write and its
    rename — and "ckpt.manifest", before the manifest update), used by
    the crash-during-save tests; None is a no-op.

    `meta` (e.g. mesh axes + elastic width, Trainer._ckpt_meta) is
    recorded per checkpoint in the manifest — what topology-change
    restore validates against. `protect` names one checkpoint file that
    pruning must never delete: the trainers pass the checkpoint the
    CURRENT run resumed from, so a crash right after a resume always
    leaves the known-good restore point in place.

    `process` (parallel/distributed.ProcessInfo) + `barrier` make the
    write multihost-safe: only process 0 touches the filesystem; every
    process then meets at the barrier, so no process can read (or exit
    into a restore) before the writer finished. Defaults keep the
    single-process behavior byte-identical.
    """
    ckpt_dir = Path(ckpt_dir)
    path = ckpt_dir / f"ckpt_{step}.npz"
    # The barrier name is keyed by STEP: if two processes ever reach
    # save_checkpoint for different steps (e.g. a preemption drain on
    # one host racing an interval save on another), the rendezvous
    # mismatch fails loudly instead of silently pairing unrelated save
    # events. Coordinating the drain step itself across hosts is the
    # missing piece of true multihost preemption — future work; today's
    # supported reality is single-process (barrier is then a no-op).
    fence = f"ckpt_save_{step}"
    if process is not None and process.process_index != 0:
        # Non-writers: just meet the writer at the barrier. The shared
        # filesystem's rename is the publication point; the barrier is
        # the ordering proof (tests/test_elastic.py multihost suite).
        if barrier is not None:
            barrier(fence)
        return path
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    flat = _flatten(jax.device_get(state))
    # Tmp is a dotfile (invisible to the ckpt_*.npz glob), so a crash
    # between write and rename can't poison later listing; it must still
    # end in .npz or np.savez appends the suffix itself.
    tmp = ckpt_dir / f".ckpt_{step}.tmp.npz"
    np.savez(tmp, **flat)
    if faults is not None:
        faults.fire("ckpt.pre_rename", step)
    tmp.rename(path)
    if faults is not None:
        faults.fire("ckpt.manifest", step)
    mf = _load_manifest(ckpt_dir) or {}
    checksums = mf.get("checksums")
    if not isinstance(checksums, dict):
        checksums = {}
    checksums[path.name] = {k: _checksum(v) for k, v in flat.items()}
    metas = mf.get("meta")
    if not isinstance(metas, dict):
        metas = {}
    if meta is not None:
        metas[path.name] = meta
    live = _list_checkpoints(ckpt_dir)
    drop = [p for p in live[:-keep] if p.name != protect]
    for p in drop:
        p.unlink()
        checksums.pop(p.name, None)
    kept = {p.name for p in live if p not in drop}
    _atomic_write_text(ckpt_dir / MANIFEST, json.dumps({
        "latest_step": step,
        "keys": sorted(flat),
        "checksums": {n: c for n, c in sorted(checksums.items())
                      if n in kept},
        "meta": {n: m for n, m in sorted(metas.items()) if n in kept},
    }, indent=2))
    if barrier is not None:
        barrier(fence)
    return path


def checkpoint_meta(ckpt_dir: str | Path, name: str) -> dict | None:
    """The manifest's per-checkpoint meta entry (mesh axes, elastic
    width, process count — whatever the writer recorded), or None for
    pre-meta checkpoints / missing manifest. Restore-side topology
    validation reads this (validate_resume_meta below)."""
    mf = _load_manifest(Path(ckpt_dir))
    if mf is None:
        return None
    metas = mf.get("meta")
    entry = metas.get(name) if isinstance(metas, dict) else None
    return entry if isinstance(entry, dict) else None


def validate_resume_meta(ckpt_path, *, mesh, elastic_width: int, metrics,
                         logger) -> None:
    """Check a restored checkpoint's recorded topology against the live
    one — shared by both trainers (ONE implementation). A changed mesh
    is the POINT of elasticity: log it and emit a topology_change obs
    event (full-array checkpoints reshard on placement). A changed
    elastic width is a hard error — the width-invariant reduction tree
    is keyed by W0, so changing it silently breaks the bitwise contract
    mid-run. Pre-meta checkpoints validate vacuously."""
    meta = checkpoint_meta(Path(ckpt_path).parent, Path(ckpt_path).name)
    if meta is None:
        return
    saved_w = meta.get("elastic_width")
    if saved_w is not None and int(saved_w) != int(elastic_width):
        raise ValueError(
            f"checkpoint {Path(ckpt_path).name} was written with "
            f"--elastic-width {saved_w}, this run uses {elastic_width}: "
            "the canonical reduction tree would change mid-run — "
            "resume with the original width"
        )
    from ..parallel.mesh import describe_mesh

    saved_mesh = meta.get("mesh") or {}
    live = describe_mesh(mesh)
    if saved_mesh and saved_mesh != live:
        metrics.log("fault", kind="topology_change", saved=saved_mesh,
                    live=live)
        logger.info(
            "topology changed across resume: checkpoint written under "
            "%s, resuming under %s (full-array checkpoints reshard on "
            "placement)", saved_mesh, live,
        )


class AsyncCheckpointer:
    """Overlap checkpoint IO with the next training steps.

    save_checkpoint() stalls the step loop for the whole device_get +
    npz write; at CNN scale that is milliseconds, but at the LM bench's
    sizes the write dominates (VERDICT round 2). Here save() snapshots
    the state to host synchronously — it must happen before the next
    step donates the buffers — and hands the arrays to ONE background
    worker that does the savez + atomic rename + prune. At most one
    write is in flight: a second save() first drains the previous one
    (bounded memory; files appear in step order). A failed write
    re-raises at the next save()/wait() — it cannot pass silently.

    async_=False degrades to the synchronous save_checkpoint, so callers
    hold one code path and a flag.
    """

    def __init__(self, ckpt_dir: str | Path, *, keep: int = 3,
                 async_: bool = True, faults=None, meta: dict | None = None,
                 process=None, barrier=None):
        self.ckpt_dir = Path(ckpt_dir)
        self.keep = keep
        self.faults = faults
        # Per-checkpoint manifest metadata (mesh/elastic topology) and
        # the resumed-from checkpoint pruning must never delete; the
        # trainer sets `protect` after a successful resume.
        self.meta = meta
        self.protect: str | None = None
        self.process = process
        self.barrier = barrier
        # The step of the most recently issued save — lets the
        # preemption drain skip re-writing a checkpoint an interval
        # save already produced on the same boundary (faults.
        # drain_preemption).
        self.last_step: int | None = None
        self._executor = None
        self._pending = None
        if async_:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt"
            )

    def _kwargs(self, barrier=None) -> dict:
        return dict(keep=self.keep, faults=self.faults, meta=self.meta,
                    protect=self.protect, process=self.process,
                    barrier=barrier)

    def save(self, state, step: int) -> None:
        """Snapshot `state` (device or host pytree) and schedule the write.

        Multihost runs (process_count > 1) save SYNCHRONOUSLY on the
        calling thread even when async_ is on: the publication barrier
        is a device collective, and a collective issued from the worker
        thread would be unordered against the main thread's train-step
        collectives — mismatched collective order across processes
        deadlocks the runtime. Correctness over overlap there; the
        single-process path (where the barrier is a no-op) keeps the
        background write."""
        self.last_step = step
        if self._executor is None or (
            self.process is not None and self.process.process_count > 1
        ):
            save_checkpoint(self.ckpt_dir, jax.device_get(state),
                            step, **self._kwargs(barrier=self.barrier))
            return
        self.wait()  # drain (and re-raise from) any in-flight write
        host = jax.device_get(state)
        # barrier=None: the worker thread must never issue collectives.
        self._pending = self._executor.submit(
            save_checkpoint, self.ckpt_dir, host, step, **self._kwargs(),
        )

    def wait(self) -> None:
        """Block until the in-flight write (if any) lands; re-raise errors."""
        if self._pending is not None:
            fut, self._pending = self._pending, None
            fut.result()

    def close(self) -> None:
        """Drain and release the worker thread. Further save() calls fall
        back to the synchronous path, so close() is safe mid-lifecycle
        (trainers close at the end of train(); a later ad-hoc save still
        works)."""
        self.wait()
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None

    # Context-manager + finalizer support: the trainers close() in a
    # finally around their loop, so an in-flight write's failure re-raises
    # (chained) even when train() itself raises between saves; __del__ is
    # the last-resort net for a dropped object — it cannot raise, so it
    # logs the lost error and releases the worker thread.
    def __enter__(self) -> AsyncCheckpointer:
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            if self._pending is not None:
                def _log_failure(fut):
                    err = fut.exception()
                    if err is not None:
                        import logging

                        logging.getLogger("mpi_cuda_cnn_tpu").error(
                            "async checkpoint write failed (object "
                            "dropped before wait/close): %r", err,
                        )

                # Fires immediately if already done, else when the
                # write lands — the in-flight case is exactly the one
                # a dropped object would otherwise lose.
                self._pending.add_done_callback(_log_failure)
            if self._executor is not None:
                self._executor.shutdown(wait=False)
        except Exception:
            pass  # interpreter teardown: never raise from __del__


def _list_checkpoints(ckpt_dir: Path) -> list[Path]:
    found = [(int(m.group(1)), p) for p in ckpt_dir.glob("ckpt_*.npz")
             if (m := _STEP_RE.search(p.name))]
    return [p for _, p in sorted(found)]


def latest_checkpoint(ckpt_dir: str | Path) -> Path | None:
    ckpt_dir = Path(ckpt_dir)
    if not ckpt_dir.is_dir():
        return None
    ckpts = _list_checkpoints(ckpt_dir)
    return ckpts[-1] if ckpts else None


def restore_checkpoint(path: str | Path, state_template, *,
                       verify: bool = True):
    """Restore into the structure of state_template (same pytree as saved).

    The template supplies the pytree structure; arrays come from the
    archive. Missing or extra keys raise — a resume must be exact.
    verify=True checks each array against the manifest's crc32s when the
    manifest records this file (CheckpointCorruptError on mismatch); a
    missing/unparsable manifest, or one without this file's entry, skips
    verification rather than blocking the restore.
    """
    path = Path(path)
    try:
        archive = np.load(path)
    except ValueError as e:
        # np.load reports unrecognized bytes as ValueError ("pickled
        # data"); keep plain ValueError for STRUCTURE mismatches below —
        # those are config bugs, this is corruption.
        raise CheckpointCorruptError(
            f"{path.name}: unreadable archive: {e}"
        ) from e
    flat_template = _flatten(state_template)
    if set(archive.files) != set(flat_template):
        missing = set(flat_template) - set(archive.files)
        extra = set(archive.files) - set(flat_template)
        raise ValueError(f"checkpoint mismatch: missing={missing} extra={extra}")
    sums = None
    if verify:
        mf = _load_manifest(path.parent)
        if mf is not None:
            entry = mf.get("checksums", {})
            sums = entry.get(path.name) if isinstance(entry, dict) else None
    leaves_paths, treedef = jax.tree_util.tree_flatten_with_path(state_template)
    new_leaves = []
    for path_keys, leaf in leaves_paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path_keys)
        arr = archive[key]
        if sums is not None and key in sums and _checksum(arr) != sums[key]:
            raise CheckpointCorruptError(
                f"{path.name}: array {key!r} fails its manifest checksum "
                "— the file is corrupt"
            )
        new_leaves.append(np.asarray(arr, dtype=np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def restore_latest(ckpt_dir: str | Path, state_template, *, logger=None,
                   metrics=None):
    """Restore the newest checkpoint that verifies, falling back past
    corrupt/truncated files to older ones.

    Returns (state, path) or (None, None) when no checkpoint restores.
    Structure mismatches (ValueError) propagate — those are config bugs,
    not corruption; corruption-class failures (checksum mismatch, a
    torn/unreadable archive) log a warning, emit a ``fault`` obs event
    when a metrics sink is given, and move on to the previous file.
    """
    import zipfile

    ckpt_dir = Path(ckpt_dir)
    if not ckpt_dir.is_dir():
        return None, None
    for path in reversed(_list_checkpoints(ckpt_dir)):
        try:
            return restore_checkpoint(path, state_template), path
        except (CheckpointCorruptError, zipfile.BadZipFile, OSError,
                EOFError, KeyError) as e:
            if logger is not None:
                logger.warning(
                    "checkpoint %s is corrupt (%s: %s); falling back to "
                    "the previous one", path.name, type(e).__name__, e,
                )
            if metrics is not None:
                metrics.log("fault", kind="ckpt_fallback", path=path.name,
                            error=f"{type(e).__name__}: {e}")
    return None, None
