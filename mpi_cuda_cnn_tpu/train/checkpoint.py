"""Checkpoint / resume.

The reference has no serialization of any kind — weights live and die in
process memory, training always restarts from random init (SURVEY.md §5.4).
This module provides the missing capability as flat `.npz` archives: the
state pytree is flattened with `jax.tree_util` key paths as array names, so
checkpoints are a stable, inspectable format independent of Python pickling
(and of this framework — `np.load` reads them anywhere).
"""

from __future__ import annotations

import json
import re
from pathlib import Path

import jax
import numpy as np

_STEP_RE = re.compile(r"ckpt_(\d+)\.npz$")


def _flatten(state) -> dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def save_checkpoint(ckpt_dir: str | Path, state, step: int, *, keep: int = 3) -> Path:
    """Write state as ckpt_{step}.npz + a small JSON manifest; prune old."""
    ckpt_dir = Path(ckpt_dir)
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    flat = _flatten(jax.device_get(state))
    path = ckpt_dir / f"ckpt_{step}.npz"
    # Tmp is a dotfile (invisible to the ckpt_*.npz glob), so a crash
    # between write and rename can't poison later listing; it must still
    # end in .npz or np.savez appends the suffix itself.
    tmp = ckpt_dir / f".ckpt_{step}.tmp.npz"
    np.savez(tmp, **flat)
    tmp.rename(path)
    (ckpt_dir / "manifest.json").write_text(
        json.dumps({"latest_step": step, "keys": sorted(flat)}, indent=2)
    )
    for p in _list_checkpoints(ckpt_dir)[:-keep]:
        p.unlink()
    return path


class AsyncCheckpointer:
    """Overlap checkpoint IO with the next training steps.

    save_checkpoint() stalls the step loop for the whole device_get +
    npz write; at CNN scale that is milliseconds, but at the LM bench's
    sizes the write dominates (VERDICT round 2). Here save() snapshots
    the state to host synchronously — it must happen before the next
    step donates the buffers — and hands the arrays to ONE background
    worker that does the savez + atomic rename + prune. At most one
    write is in flight: a second save() first drains the previous one
    (bounded memory; files appear in step order). A failed write
    re-raises at the next save()/wait() — it cannot pass silently.

    async_=False degrades to the synchronous save_checkpoint, so callers
    hold one code path and a flag.
    """

    def __init__(self, ckpt_dir: str | Path, *, keep: int = 3,
                 async_: bool = True):
        self.ckpt_dir = Path(ckpt_dir)
        self.keep = keep
        self._executor = None
        self._pending = None
        if async_:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt"
            )

    def save(self, state, step: int) -> None:
        """Snapshot `state` (device or host pytree) and schedule the write."""
        if self._executor is None:
            save_checkpoint(self.ckpt_dir, jax.device_get(state),
                            step, keep=self.keep)
            return
        self.wait()  # drain (and re-raise from) any in-flight write
        host = jax.device_get(state)
        self._pending = self._executor.submit(
            save_checkpoint, self.ckpt_dir, host, step, keep=self.keep
        )

    def wait(self) -> None:
        """Block until the in-flight write (if any) lands; re-raise errors."""
        if self._pending is not None:
            fut, self._pending = self._pending, None
            fut.result()

    def close(self) -> None:
        """Drain and release the worker thread. Further save() calls fall
        back to the synchronous path, so close() is safe mid-lifecycle
        (trainers close at the end of train(); a later ad-hoc save still
        works)."""
        self.wait()
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None

    # Context-manager + finalizer support: the trainers close() in a
    # finally around their loop, so an in-flight write's failure re-raises
    # (chained) even when train() itself raises between saves; __del__ is
    # the last-resort net for a dropped object — it cannot raise, so it
    # logs the lost error and releases the worker thread.
    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            if self._pending is not None:
                def _log_failure(fut):
                    err = fut.exception()
                    if err is not None:
                        import logging

                        logging.getLogger("mpi_cuda_cnn_tpu").error(
                            "async checkpoint write failed (object "
                            "dropped before wait/close): %r", err,
                        )

                # Fires immediately if already done, else when the
                # write lands — the in-flight case is exactly the one
                # a dropped object would otherwise lose.
                self._pending.add_done_callback(_log_failure)
            if self._executor is not None:
                self._executor.shutdown(wait=False)
        except Exception:
            pass  # interpreter teardown: never raise from __del__


def _list_checkpoints(ckpt_dir: Path) -> list[Path]:
    found = [(int(m.group(1)), p) for p in ckpt_dir.glob("ckpt_*.npz")
             if (m := _STEP_RE.search(p.name))]
    return [p for _, p in sorted(found)]


def latest_checkpoint(ckpt_dir: str | Path) -> Path | None:
    ckpt_dir = Path(ckpt_dir)
    if not ckpt_dir.is_dir():
        return None
    ckpts = _list_checkpoints(ckpt_dir)
    return ckpts[-1] if ckpts else None


def restore_checkpoint(path: str | Path, state_template):
    """Restore into the structure of state_template (same pytree as saved).

    The template supplies the pytree structure; arrays come from the
    archive. Missing or extra keys raise — a resume must be exact.
    """
    archive = np.load(Path(path))
    flat_template = _flatten(state_template)
    if set(archive.files) != set(flat_template):
        missing = set(flat_template) - set(archive.files)
        extra = set(archive.files) - set(flat_template)
        raise ValueError(f"checkpoint mismatch: missing={missing} extra={extra}")
    leaves_paths, treedef = jax.tree_util.tree_flatten_with_path(state_template)
    new_leaves = []
    for path_keys, leaf in leaves_paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path_keys)
        arr = archive[key]
        new_leaves.append(np.asarray(arr, dtype=np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
