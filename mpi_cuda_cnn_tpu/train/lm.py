"""Language-model training: the transformer's train step + loss.

The reference's training loop is CNN-only (cnn.c:445-474); this module is
its twin for the framework's long-context model family (models/
transformer.py). One jitted step — forward, causal-LM cross-entropy,
backward, optimizer update — with the TPU levers exposed:

- `attn_impl`: "flash" (the fused Pallas kernel pair,
  ops/pallas_attention.py) is the default on TPU; "oracle" is the
  quadratic jnp reference; "auto" picks per backend/shape.
- `compute_dtype`: bfloat16 runs every matmul on the MXU's native path
  (master params stay f32 — mixed precision, not low-precision training).
- `remat`: jax.checkpoint per block (activation memory for FLOPs).

Sequence-parallel training lives in parallel/sp.py (shard_map over a
'seq' axis); this step is the single-device / pure-DP form. For DP, jit
partitions it over the mesh from the state/batch shardings (GSPMD), the
same design as parallel/tp.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..models.transformer import TransformerLM
from ..utils.donation import donate_jit


# Measured f32 oracle/flash crossover (scripts/bench_crossover.py on one
# v5e, round 4, HEAD kernels — full f32 train step at b=2, depth=4,
# two-point timing, TWO independent captures):
#   s=2048: flash 28.2 vs 31.1 ms, then 32.7 vs 30.9  <- flips run-to-run
#   s=3072: flash 61.5 vs 61.6,    then 57.3 vs 57.6  <- flash, both runs
#   s=4096: flash 87.4 vs 91.6,    then 87.8 vs 95.5
#   s=6144: flash 160.4 vs 183.1,  then 161.1 vs 178.0
# The bound sits where flash wins RELIABLY: s=2048 is a coin flip within
# the tunnel's noise band (bench_lm's b=8/depth=8 matrix also had the
# oracle up 8% there), so it routes to the oracle — also the f32
# accuracy story — and every measured point from 3072 up routes to
# flash. Throughput runs use bf16, where flash wins 2.2x outright at
# every 128-aligned length.
_F32_FLASH_MIN_SEQ = 3072


def pick_attn_impl(impl: str, seq_len: int, compute_dtype=None) -> str:
    """Resolve "auto" to a concrete attention implementation.

    Measurement-driven (PERF.md, one v5e): the fused flash kernel wins
    wherever its block constraint (S % 128 == 0) holds on a real TPU
    *except* f32 at short sequences, where the oracle's default-precision
    XLA matmuls beat the f32 kernel's HIGHEST-precision dots — there the
    oracle is both faster and the f32 path's accuracy story. On CPU the
    oracle always wins (interpret-mode Pallas is orders of magnitude
    slower than XLA — correct, but only for tests).
    """
    if impl != "auto":
        return impl
    if jax.default_backend() != "tpu" or seq_len % 128 != 0:
        return "oracle"
    f32 = compute_dtype is None or jnp.dtype(compute_dtype) == jnp.float32
    if f32 and seq_len < _F32_FLASH_MIN_SEQ:
        return "oracle"
    return "flash"


def get_attn_fn(impl: str):
    """Concrete attention callable (q, k, v) -> o, causal, for `impl`."""
    if impl == "flash":
        from ..ops.pallas_attention import flash_attention

        return lambda q, k, v: flash_attention(q, k, v, True)
    if impl == "oracle":
        from ..ops.attention import attention

        return lambda q, k, v: attention(q, k, v, causal=True)
    raise ValueError(
        f"unknown attention impl {impl!r}; use 'flash' or 'oracle' "
        "(resolve 'auto' with pick_attn_impl first)"
    )


def lm_loss(
    model: TransformerLM,
    params,
    tokens,
    targets,
    *,
    attn_fn=None,
    compute_dtype=None,
    remat: bool = False,
    moe_aux_weight: float = 0.01,
    ce_chunk: int = 0,
    moe_axis: str | None = None,
    moe_dispatch_chunk: int = 0,
    moe_dispatch_dtype=None,
):
    """Mean next-token NLL (+ the Switch aux loss when the model is MoE).
    tokens/targets: (B, S) int32. The loss softmax always runs in f32.
    moe_axis names a mesh axis for expert-parallel dispatch inside a
    shard_map caller (parallel/ep.py make_ep_lm_train_step); None keeps
    the local dense dispatch. moe_dispatch_chunk > 0 routes MoE tokens
    in chunks (ep.moe_mlp dispatch_chunk — the single-chip lever for the
    quadratic dispatch-einsum term; incompatible with moe_axis).

    ce_chunk > 0 fuses the head matmul into a chunked cross-entropy: the
    final-LN features go through the head in S-chunks of that size inside
    a lax.scan, each chunk's NLL computed and reduced under
    jax.checkpoint — the (B, S, V) f32 logits are NEVER materialized
    (peak extra memory O(B * chunk * V), recomputed in backward). At
    vocab 8k x s 2k x b 8 the dense logits are 512 MB of HBM traffic; at
    32k+ vocab they stop fitting at all — this is the standard fix.
    ce_chunk must divide S; 0 keeps the dense path.
    """
    if ce_chunk:
        from ..ops.losses import chunked_ce_mean

        feats, aux = model.apply(
            params, tokens, attn_fn=attn_fn, remat=remat,
            compute_dtype=compute_dtype, return_aux=True,
            return_features=True, moe_axis=moe_axis,
            moe_dispatch_chunk=moe_dispatch_chunk,
            moe_dispatch_dtype=moe_dispatch_dtype,
        )
        nll = chunked_ce_mean(
            feats, params["head"], targets, ce_chunk, compute_dtype
        )
        return nll + moe_aux_weight * aux
    logits, aux = model.apply(
        params, tokens, attn_fn=attn_fn, remat=remat,
        compute_dtype=compute_dtype, return_aux=True, moe_axis=moe_axis,
        moe_dispatch_chunk=moe_dispatch_chunk,
        moe_dispatch_dtype=moe_dispatch_dtype,
    )
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll) + moe_aux_weight * aux


def make_lm_train_step(
    model: TransformerLM,
    optimizer,
    *,
    attn_impl: str = "auto",
    seq_len: int | None = None,
    compute_dtype=None,
    remat: bool = False,
    donate: bool = True,
    moe_aux_weight: float = 0.01,
    ce_chunk: int = 0,
    grad_accum: int = 1,
    moe_dispatch_chunk: int = 0,
    moe_dispatch_dtype=None,
    accum_dtype=None,
):
    """step(state, tokens, targets) -> (state, {"loss": ...}), jitted.

    accum_dtype (jnp.bfloat16 or the string "bfloat16") stores the
    grad-accumulation carry in that dtype — halves the per-microbatch
    grad-tree HBM traffic that bounds the grad-accum MFU ladder
    (dp._local_grads for the accuracy band; only meaningful with
    grad_accum > 1, ignored otherwise).

    state = {"params", "opt_state", "step"} — the same pytree-of-arrays
    state scheme as every other train step (checkpointable by
    train/checkpoint.py unchanged). Under a multi-device mesh, place the
    state replicated (or FSDP-sharded) and the batch data-sharded; jit
    inserts the psums (GSPMD).

    grad_accum > 1 accumulates per-micro-batch value_and_grad inside a
    lax.scan (parallel/dp.py _local_grads — the ONE accumulation
    implementation, shared with the CNN path): the backward runs
    micro-batch-by-micro-batch (no autodiff THROUGH the scan), so peak
    activation memory is one micro-batch's while the optimizer sees the
    exact full-batch mean gradient (equal micro-batches make the mean
    of means the batch mean; parity-tested — MoE's per-chunk routing
    statistics are the same estimator change as every microbatched
    trainer's). Must divide the batch.
    """
    import optax

    if accum_dtype is not None:
        accum_dtype = jnp.dtype(accum_dtype)
    impl = pick_attn_impl(attn_impl, seq_len or model.max_seq, compute_dtype)
    attn_fn = get_attn_fn(impl)
    loss = partial(
        lm_loss, model, attn_fn=attn_fn, compute_dtype=compute_dtype,
        remat=remat, moe_aux_weight=moe_aux_weight, ce_chunk=ce_chunk,
        moe_dispatch_chunk=moe_dispatch_chunk,
        moe_dispatch_dtype=moe_dispatch_dtype,
    )

    @partial(donate_jit, donate=donate)
    def step(state, tokens, targets):
        if grad_accum > 1 and tokens.shape[0] % grad_accum:
            raise ValueError(
                f"batch {tokens.shape[0]} not divisible by grad_accum "
                f"{grad_accum}"
            )
        # ONE accumulation implementation for both families: dp.py's
        # helper carries the interleaved micro-split (a contiguous split
        # would hand each micro-batch to a single device under GSPMD
        # batch sharding) and the scan that keeps one micro-batch of
        # activations live.
        from ..parallel.dp import local_grads_no_aux

        l, grads = local_grads_no_aux(
            loss, state["params"], tokens, targets, grad_accum,
            accum_dtype=accum_dtype,
        )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return (
            {"params": params, "opt_state": opt_state,
             "step": state["step"] + 1},
            {"loss": l},
        )

    return step


def make_elastic_lm_train_step(
    model: TransformerLM,
    optimizer,
    mesh,
    *,
    elastic_width: int,
    attn_impl: str = "auto",
    seq_len: int | None = None,
    compute_dtype=None,
    remat: bool = False,
    donate: bool = True,
    moe_aux_weight: float = 0.01,
    ce_chunk: int = 0,
):
    """The LM train step with the width-invariant gradient reduction
    (parallel/elastic.py) — the elastic twin of make_lm_train_step.

    The plain LM step is a GSPMD jit: data parallelism falls out of the
    batch sharding, and XLA chooses how the batch reductions partition —
    which is exactly what changes bit patterns when the width changes.
    This step is an explicit shard_map over the 'data' axis instead, so
    the gradient is the canonical balanced-tree sum over fixed-size
    microbatches at every width: a run preempted at dp=4 and resumed at
    dp=2 stays on the uninterrupted run's bitwise trajectory (ISSUE 5;
    proven in tests/test_elastic.py). Pure-DP meshes only — the trainer
    rejects elastic_width on seq/model/pipe/expert meshes.
    """
    import optax
    from jax.sharding import PartitionSpec as P

    from ..parallel.elastic import elastic_grads
    from ..parallel.mesh import DATA_AXIS

    impl = pick_attn_impl(attn_impl, seq_len or model.max_seq, compute_dtype)
    attn_fn = get_attn_fn(impl)
    loss = partial(
        lm_loss, model, attn_fn=attn_fn, compute_dtype=compute_dtype,
        remat=remat, moe_aux_weight=moe_aux_weight, ce_chunk=ce_chunk,
    )
    n_data = mesh.shape.get(DATA_AXIS, 1)

    def step(state, tokens, targets):
        def grad_fn(px, py):
            l, grads = jax.value_and_grad(loss)(state["params"], px, py)
            return l, grads

        l, grads = elastic_grads(
            grad_fn, tokens, targets, elastic_width=elastic_width,
            axis=DATA_AXIS, axis_size=n_data,
        )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return (
            {"params": params, "opt_state": opt_state,
             "step": state["step"] + 1},
            {"loss": l},
        )

    sharded = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return donate_jit(sharded, donate=donate), impl


def make_lm_state(model: TransformerLM, optimizer, seed: int = 0) -> dict:
    """Fresh {"params", "opt_state", "step"} for the LM train step."""
    params = model.init(jax.random.key(seed))
    return {
        "params": params,
        "opt_state": optimizer.init(params),
        "step": jnp.zeros((), jnp.int32),
    }


def lm_flops_per_token(model: TransformerLM, seq_len: int) -> float:
    """Analytic forward+backward FLOPs per trained token (the MFU
    denominator; backward = 2x forward, the standard accounting).

    Per layer forward, per token: q proj 2d², kv proj 4·d·(Hkv·hd)
    (= 4d² for MHA, less under GQA), attn-out 2d², MLP 16d²·k where
    k = moe_top_k for MoE blocks (each routed token runs k experts of
    the same 4d hidden size; Switch k=1 matches dense, GShard k=2
    doubles the MLP work) plus the router 2·d·E, plus attention
    scores+values 2·s·d (causal: each query sees s/2 keys on average;
    QK^T and P·V each cost 2·(s/2)·d). Embedding head: 2·d·V.
    """
    d, s, v = model.dim, seq_len, model.vocab
    kv_dim = model.n_kv * model.head_dim
    k = model.moe_top_k if model.moe_experts else 1
    mlp = 16 * d * d * k
    gate = 2 * d * model.moe_experts if model.moe_experts else 0
    per_layer = (
        2 * d * d + 4 * d * kv_dim + 2 * d * d + mlp + gate + 2 * s * d
    )
    fwd = model.depth * per_layer + 2 * d * v
    return 3.0 * fwd


def count_params(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
