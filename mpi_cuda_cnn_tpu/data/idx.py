"""MNIST IDX binary format reader/writer.

Format (as parsed by the reference C loader, cnn.c:352-383):

    byte 0-1   u16 magic, must be 0
    byte 2     u8  element type code (0x08 = unsigned byte is all MNIST uses)
    byte 3     u8  ndims
    then       ndims big-endian u32 dimension sizes
    then       prod(dims) payload bytes (for type 0x08)

The reference validates magic==0, type==0x08, ndims>=1 (cnn.c:361-363) and
reads dims with be32toh (cnn.c:374). Three of its four variants malloc the
payload but never fread it (SURVEY.md 2.8) — a bug we obviously do not
reproduce. Unlike the reference we support the full IDX type-code table so
golden-file tensors can round-trip through the same container.
"""

from __future__ import annotations

import gzip
import struct
from pathlib import Path

import numpy as np

# IDX type code -> numpy dtype. MNIST itself only uses 0x08.
_IDX_DTYPES = {
    0x08: np.dtype(">u1"),
    0x09: np.dtype(">i1"),
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}
_DTYPE_CODES = {v.newbyteorder("="): k for k, v in _IDX_DTYPES.items()}


class IdxError(ValueError):
    """Malformed IDX container (bad magic/type/dims or truncated payload)."""


def read_idx(path: str | Path) -> np.ndarray:
    """Read an IDX file (optionally .gz) into a little-endian numpy array.

    Validation mirrors the reference parser (cnn.c:361-363): zero magic,
    known type code, at least one dimension. Truncated payloads raise
    IdxError instead of returning uninitialized memory (reference bug,
    SURVEY.md 2.8).
    """
    path = Path(path)
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        header = f.read(4)
        if len(header) != 4:
            raise IdxError(f"{path}: truncated IDX header")
        magic, type_code, ndims = struct.unpack(">HBB", header)
        if magic != 0:
            raise IdxError(f"{path}: bad IDX magic {magic:#x} (expected 0)")
        if type_code not in _IDX_DTYPES:
            raise IdxError(f"{path}: unknown IDX type code {type_code:#x}")
        if ndims < 1:
            raise IdxError(f"{path}: IDX ndims must be >= 1, got {ndims}")
        dim_bytes = f.read(4 * ndims)
        if len(dim_bytes) != 4 * ndims:
            raise IdxError(f"{path}: truncated IDX dimension table")
        dims = struct.unpack(f">{ndims}I", dim_bytes)
        dtype = _IDX_DTYPES[type_code]
        count = int(np.prod(dims, dtype=np.int64))
        payload = f.read(count * dtype.itemsize)
        if len(payload) != count * dtype.itemsize:
            raise IdxError(
                f"{path}: truncated IDX payload "
                f"({len(payload)} of {count * dtype.itemsize} bytes)"
            )
    arr = np.frombuffer(payload, dtype=dtype).reshape(dims)
    return arr.astype(dtype.newbyteorder("="))


def write_idx(path: str | Path, arr: np.ndarray) -> None:
    """Write a numpy array as an IDX file (gzipped iff path ends in .gz)."""
    arr = np.asarray(arr)
    dtype = arr.dtype.newbyteorder("=")
    if dtype not in _DTYPE_CODES:
        raise IdxError(f"dtype {arr.dtype} has no IDX type code")
    code = _DTYPE_CODES[dtype]
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    header = struct.pack(">HBB", 0, code, arr.ndim)
    dims = struct.pack(f">{arr.ndim}I", *arr.shape)
    payload = arr.astype(arr.dtype.newbyteorder(">")).tobytes()
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "wb") as f:
        f.write(header)
        f.write(dims)
        f.write(payload)
