"""Dataset registry.

The reference trains on exactly one dataset — MNIST loaded from four IDX
files given as positional CLI args (cnn.c:406-443). The benchmark configs
(BASELINE.json) additionally name Fashion-MNIST (same container format) and
CIFAR-10 (32x32x3 input path). This registry serves all of them from IDX
files on disk, and provides deterministic synthetic generators of the same
shapes so every test and benchmark runs without network access.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from collections.abc import Callable

import numpy as np

from .idx import read_idx, write_idx


@dataclasses.dataclass(frozen=True)
class Dataset:
    """An in-memory image-classification dataset.

    images: uint8, (N, H, W) grayscale or (N, H, W, C) color.
    labels: uint8/int, (N,).
    """

    name: str
    train_images: np.ndarray
    train_labels: np.ndarray
    test_images: np.ndarray
    test_labels: np.ndarray
    num_classes: int

    @property
    def input_shape(self) -> tuple[int, ...]:
        hwc = self.train_images.shape[1:]
        return hwc if len(hwc) == 3 else (*hwc, 1)

    def __post_init__(self):
        for split in ("train", "test"):
            imgs = getattr(self, f"{split}_images")
            labels = getattr(self, f"{split}_labels")
            if len(imgs) != len(labels):
                raise ValueError(
                    f"{self.name}/{split}: {len(imgs)} images vs {len(labels)} labels"
                )


def load_idx_dataset(
    name: str,
    train_images: str | Path,
    train_labels: str | Path,
    test_images: str | Path,
    test_labels: str | Path,
    num_classes: int = 10,
) -> Dataset:
    """Load a dataset from four IDX paths — the reference's CLI contract
    (cnn.c:408-411: train-images train-labels test-images test-labels).

    Refuses a directory carrying the SYNTHETIC-DATA sentinel
    (scripts/get_mnist.py's network-free fallback marker): those files
    are stripes under MNIST filenames, and a run that loaded them would
    report itself as real-data — the poisoned-cache path VERDICT weak #1
    closed. Use `--dataset synthetic` to train on them knowingly."""
    for p in (train_images, train_labels, test_images, test_labels):
        marker = Path(p).parent / "SYNTHETIC-DATA"
        if marker.exists():
            from .idx import IdxError

            raise IdxError(
                f"{Path(p).parent} is marked SYNTHETIC-DATA (the "
                "network-free fallback of scripts/get_mnist.py wrote "
                "synthetic bytes under real dataset filenames); refusing "
                "to label this run as real data — re-run `make get_mnist` "
                "with network, or train on `--dataset synthetic` "
                "explicitly"
            )
    return Dataset(
        name=name,
        train_images=read_idx(train_images),
        train_labels=read_idx(train_labels),
        test_images=read_idx(test_images),
        test_labels=read_idx(test_labels),
        num_classes=num_classes,
    )


# ---------------------------------------------------------------------------
# Synthetic data
# ---------------------------------------------------------------------------


def synthetic_stripes(
    num_train: int = 2000,
    num_test: int = 500,
    height: int = 28,
    width: int = 28,
    channels: int = 1,
    num_classes: int = 10,
    noise: float = 16.0,
    seed: int = 1234,
    name: str = "synthetic",
) -> Dataset:
    """Learnable synthetic dataset: class k lights up horizontal stripe k.

    Same family of pattern the survey used to validate the C reference
    (SURVEY.md §4: 500/500 test accuracy after 10 epochs), so convergence
    tests carry over directly. Images are uint8 with Gaussian noise.
    """
    rng = np.random.default_rng(seed)
    band = height // num_classes

    def make(n: int):
        labels = rng.integers(0, num_classes, size=n).astype(np.uint8)
        imgs = rng.normal(32.0, noise, size=(n, height, width, channels))
        for k in range(num_classes):
            rows = slice(k * band, (k + 1) * band)
            imgs[labels == k, rows, :, :] += 160.0
        imgs = np.clip(imgs, 0, 255).astype(np.uint8)
        if channels == 1:
            imgs = imgs[..., 0]
        return imgs, labels

    train_x, train_y = make(num_train)
    test_x, test_y = make(num_test)
    return Dataset(name, train_x, train_y, test_x, test_y, num_classes)


def sklearn_digits(
    upscale: int = 28,
    test_fraction: float = 0.2,
    seed: int = 0,
    name: str = "digits",
) -> Dataset:
    """REAL handwritten digits, network-free: scikit-learn's bundled UCI
    digits set (1,797 images, 8x8, intensities 0-16). Upscaled to
    `upscale` x `upscale` (nearest-neighbor) so the MNIST-shaped model
    presets run unchanged; intensities rescaled to 0-255.

    This is the only real (non-synthetic) image data available in a
    zero-egress environment — the honest accuracy demonstration between
    synthetic stripes and true MNIST (which `make get_mnist` fetches when
    there IS network).
    """
    from sklearn.datasets import load_digits

    d = load_digits()
    imgs = (d.images * (255.0 / 16.0)).astype(np.uint8)   # (N, 8, 8)
    if upscale < 8:
        raise ValueError(f"upscale {upscale} must be >= 8")
    if upscale != 8:
        # Nearest-neighbor upscale by the floor factor, then center-pad
        # with zeros to the exact target (28 = 3x8 + 2+2 of border).
        reps = upscale // 8
        imgs = np.repeat(np.repeat(imgs, reps, axis=1), reps, axis=2)
        pad = upscale - 8 * reps
        lo, hi = pad // 2, pad - pad // 2
        imgs = np.pad(imgs, ((0, 0), (lo, hi), (lo, hi)))
    labels = d.target.astype(np.uint8)
    rng = np.random.default_rng(seed)
    order = rng.permutation(len(imgs))
    n_test = int(len(imgs) * test_fraction)
    test_idx, train_idx = order[:n_test], order[n_test:]
    return Dataset(
        name,
        imgs[train_idx], labels[train_idx],
        imgs[test_idx], labels[test_idx],
        num_classes=10,
    )


def write_synthetic_idx(dirpath: str | Path, ds: Dataset) -> dict[str, Path]:
    """Materialize a dataset as the four IDX files the CLI contract expects."""
    dirpath = Path(dirpath)
    paths = {
        "train_images": dirpath / "train-images-idx3-ubyte",
        "train_labels": dirpath / "train-labels-idx1-ubyte",
        "test_images": dirpath / "t10k-images-idx3-ubyte",
        "test_labels": dirpath / "t10k-labels-idx1-ubyte",
    }
    write_idx(paths["train_images"], ds.train_images)
    write_idx(paths["train_labels"], ds.train_labels)
    write_idx(paths["test_images"], ds.test_images)
    write_idx(paths["test_labels"], ds.test_labels)
    return paths


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

_REGISTRY: dict[str, Callable[..., Dataset]] = {}


def register_dataset(name: str, factory: Callable[..., Dataset]) -> None:
    _REGISTRY[name] = factory


def get_dataset(name: str, data_dir: str | Path | None = None, **kwargs) -> Dataset:
    """Fetch a dataset by name.

    Known names: mnist, fashion_mnist (IDX files under data_dir),
    cifar10 (IDX-converted files under data_dir), synthetic,
    synthetic_cifar. Unknown names raise KeyError listing options.
    """
    if name in _REGISTRY:
        return _REGISTRY[name](data_dir=data_dir, **kwargs)
    raise KeyError(f"unknown dataset {name!r}; known: {sorted(_REGISTRY)}")


def _idx_factory(dataset_name: str, num_classes: int = 10):
    def factory(data_dir=None, **kwargs):
        if data_dir is None:
            raise ValueError(f"{dataset_name} requires data_dir with IDX files")
        d = Path(data_dir)
        return load_idx_dataset(
            dataset_name,
            d / "train-images-idx3-ubyte",
            d / "train-labels-idx1-ubyte",
            d / "t10k-images-idx3-ubyte",
            d / "t10k-labels-idx1-ubyte",
            num_classes=num_classes,
        )

    return factory


register_dataset("mnist", _idx_factory("mnist"))
register_dataset("fashion_mnist", _idx_factory("fashion_mnist"))
register_dataset("cifar10", _idx_factory("cifar10"))
register_dataset(
    "synthetic", lambda data_dir=None, **kw: synthetic_stripes(name="synthetic", **kw)
)
register_dataset("digits", lambda data_dir=None, **kw: sklearn_digits(**kw))
register_dataset(
    "synthetic_cifar",
    lambda data_dir=None, **kw: synthetic_stripes(
        name="synthetic_cifar",
        height=32,
        width=32,
        channels=3,
        **kw,
    ),
)
