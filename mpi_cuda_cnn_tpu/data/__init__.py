"""Data subsystem: IDX format IO, dataset registry, input pipelines."""

from .idx import IdxError, read_idx, write_idx
from .datasets import Dataset, get_dataset, register_dataset, synthetic_stripes
from .pipeline import normalize_images, one_hot, epoch_batches

__all__ = [
    "IdxError",
    "read_idx",
    "write_idx",
    "Dataset",
    "get_dataset",
    "register_dataset",
    "synthetic_stripes",
    "normalize_images",
    "one_hot",
    "epoch_batches",
]
