"""On-device data augmentation.

The reference has no augmentation of any kind (its full input pipeline is
normalize + one-hot, cnn.c:457-464). The north-star accuracy target
(>=99% MNIST test accuracy, BASELINE.json) is out of reach for plain
SGD on un-augmented MNIST at LeNet scale, so augmentation is a
capability the benchmark implies; it is off by default (reference
semantics) and enabled with --augment.

Everything here is pure JAX on already-normalized float batches, designed
to run INSIDE the jitted train step (including the scanned epoch): static
shapes, per-sample PRNG keys, no host round-trip. The caller supplies one
key per (step, device) — see parallel/dp.py — and per-sample keys are
folded in here.

Specs:
  "none"        identity (the default; reference parity)
  "shift"       random +/-pad-pixel translation with zero fill (the classic
                MNIST augmentation)
  "shift-flip"  shift + random horizontal flip (CIFAR-style; flipping
                digits would hurt MNIST)
"""

from __future__ import annotations

from collections.abc import Callable

import jax
import jax.numpy as jnp

AugmentFn = Callable[[jax.Array, jnp.ndarray], jnp.ndarray]

SPECS = ("none", "shift", "shift-flip")


def _shift_one(key: jax.Array, img: jnp.ndarray, pad: int) -> jnp.ndarray:
    """Random translation of one (H, W, C) image by up to +/-pad pixels:
    zero-pad then dynamic-crop at a random corner. Static output shape, so
    it scans/jits cleanly."""
    h, w, c = img.shape
    padded = jnp.pad(img, ((pad, pad), (pad, pad), (0, 0)))
    oy, ox = jax.random.randint(key, (2,), 0, 2 * pad + 1)
    return jax.lax.dynamic_slice(padded, (oy, ox, 0), (h, w, c))


def _flip_one(key: jax.Array, img: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(jax.random.bernoulli(key), img[:, ::-1, :], img)


def make_augment(spec: str, *, pad: int = 2) -> AugmentFn | None:
    """Build augment(key, x) for a batch x: (B, H, W, C) float.

    Returns None for "none" so callers can skip the whole machinery (and
    the per-step key derivation) when augmentation is off.
    """
    if spec == "none":
        return None
    if spec not in SPECS:
        raise ValueError(f"unknown augment spec {spec!r}; one of {SPECS}")
    with_flip = spec == "shift-flip"

    def augment(key: jax.Array, x: jnp.ndarray) -> jnp.ndarray:
        keys = jax.random.split(key, x.shape[0] * 2).reshape(x.shape[0], 2)

        def one(kpair, img):
            img = _shift_one(kpair[0], img, pad)
            if with_flip:
                img = _flip_one(kpair[1], img)
            return img

        return jax.vmap(one)(keys, x)

    return augment
