"""Input pipeline: normalization, one-hot, batching.

The reference's pipeline is three lines inside the training loop: pick a
random index with replacement (cnn.c:455), divide pixel bytes by 255
(cnn.c:457), one-hot the label (cnn.c:462-464). The TPU-idiomatic
equivalent is whole-epoch permutation batching with static batch shapes —
per-sample steps would leave the MXU idle (SURVEY.md §7 hard-part (a)).

Everything here is host-side numpy; arrays cross to the device once per
step (or once per epoch for small datasets) as full batches.
"""

from __future__ import annotations

from collections.abc import Iterator

import numpy as np


# The normalization contract (cnn.c:457): pixel byte / 255 -> [0,1] float.
# Shared by the host path (normalize_images) and the on-device scan body
# (parallel/dp.py make_dp_scan_epoch); test_scan_matches_per_batch_loop
# asserts the two stay equivalent.
PIXEL_SCALE = 255.0


def ensure_channel_axis(images: np.ndarray) -> np.ndarray:
    """(N,H,W) grayscale -> (N,H,W,1); NHWC input passes through."""
    images = np.asarray(images)
    if images.ndim == 3:
        images = images[..., None]
    return images


def normalize_images(images: np.ndarray) -> np.ndarray:
    """uint8 [0,255] -> float32 [0,1], adding a channel axis for grayscale.

    Matches the reference's `x[j] = img[j]/255.0` (cnn.c:457), in f32 rather
    than double (SURVEY.md §7 hard-part (b)). Output layout is NHWC.
    """
    images = ensure_channel_axis(images)
    return images.astype(np.float32) / np.float32(PIXEL_SCALE)


def one_hot(labels: np.ndarray, num_classes: int) -> np.ndarray:
    """Labels -> float32 one-hot rows (cnn.c:462-464)."""
    labels = np.asarray(labels)
    out = np.zeros((len(labels), num_classes), dtype=np.float32)
    out[np.arange(len(labels)), labels] = 1.0
    return out


def epoch_batches(
    images: np.ndarray,
    labels: np.ndarray,
    batch_size: int,
    *,
    rng: np.random.Generator | None = None,
    drop_remainder: bool = True,
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Yield shuffled (images, labels) batches covering one epoch.

    The reference samples with replacement (cnn.c:455); an epoch permutation
    is the standard equivalent with identical expected gradient and better
    coverage. With rng=None the order is sequential (the MPI variant's
    behavior, cnnmpi.c:469). Static batch shapes: the tail partial batch is
    dropped by default so every step traces to the same XLA program.
    """
    n = len(images)
    order = np.arange(n) if rng is None else rng.permutation(n)
    end = n - (n % batch_size) if drop_remainder else n
    for start in range(0, end, batch_size):
        idx = order[start : start + batch_size]
        yield images[idx], labels[idx]
