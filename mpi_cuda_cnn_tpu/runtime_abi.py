"""Python side of the C ABI (native/tpu_abi.h).

A module-level singleton trainer driven by simple string-in/string-out
calls, so the embedded-CPython boundary stays trivial: the C driver sends
one JSON config at init and receives one JSON metrics line per call.
State (params, optimizer, compiled step) lives here — device-resident for
the life of the process, unlike the reference's per-call device round
trips (SURVEY.md §3.3).
"""

from __future__ import annotations

import json

import jax

_STATE: dict = {}


def init(config_json: str) -> str:
    from .cli import _select_device
    from .data.datasets import get_dataset, load_idx_dataset
    from .models.presets import get_model
    from .train.trainer import Trainer
    from .utils.config import Config
    from .utils.logging import MetricsLogger, get_logger

    cfg = Config.from_json(config_json)
    if not _select_device(cfg, get_logger()):
        raise RuntimeError(f"device {cfg.device!r} unavailable")
    if cfg.dataset == "idx":
        ds = load_idx_dataset(
            "idx", cfg.train_images, cfg.train_labels,
            cfg.test_images, cfg.test_labels,
        )
    else:
        ds = get_dataset(cfg.dataset, data_dir=cfg.data_dir)
    model = get_model(cfg.model, input_shape=ds.input_shape)
    trainer = Trainer(model, ds, cfg, metrics=MetricsLogger(echo=False))
    _STATE.update(trainer=trainer, cfg=cfg, epoch=0)
    return json.dumps({"ok": True, "model": model.name,
                       "n_params": model.num_params(trainer.state["params"])})


def _trainer():
    if "trainer" not in _STATE:
        raise RuntimeError("runtime_abi.init() not called")
    return _STATE["trainer"]


def train_epoch() -> str:
    """Run one epoch via Trainer.run_epoch (the same loop the Python CLI
    uses — one implementation, one shuffle stream); returns metrics JSON."""
    t = _trainer()
    metrics = t.run_epoch(_STATE["epoch"])
    _STATE["epoch"] += 1
    metrics["seconds"] = round(metrics["seconds"], 3)
    return json.dumps(metrics)


def evaluate() -> str:
    ntests, ncorrect = _trainer().evaluate()
    return json.dumps({"ntests": ntests, "ncorrect": ncorrect})


def lm_init(config_json: str) -> str:
    """LM twin of init(): build an LMTrainer from an LMConfig JSON.

    The C driver's `lm` mode drives the SAME product loop the Python
    `lm` subcommand uses (train/lm_trainer.py) — one implementation of
    corpus loading, the mesh dispatch, and checkpointing, reachable from
    both front ends.
    """
    from .cli import _select_device
    from .train.lm_trainer import LMTrainer
    from .utils.config import LMConfig
    from .utils.logging import MetricsLogger, get_logger

    cfg = LMConfig.from_json(config_json)
    if not _select_device(cfg, get_logger()):
        raise RuntimeError(f"device {cfg.device!r} unavailable")
    trainer = LMTrainer(cfg, metrics=MetricsLogger(echo=False))
    from .train.lm import count_params

    _STATE["lm"] = trainer
    return json.dumps({
        "ok": True,
        "vocab": trainer.model.vocab,
        "n_params": count_params(trainer.state["params"]),
    })


def lm_train() -> str:
    """Run the configured LM training (cfg.steps optimizer steps, eval at
    the end) and return the LMResult as one JSON line."""
    import dataclasses

    if "lm" not in _STATE:
        raise RuntimeError("runtime_abi.lm_init() not called")
    res = _STATE["lm"].train()
    out = dataclasses.asdict(res)
    out["tokens_per_s"] = round(out["tokens_per_s"], 1)
    for k in ("final_loss", "eval_loss", "eval_ppl"):
        out[k] = round(out[k], 4)
    return json.dumps(out)


def save(path: str) -> str:
    from .train.checkpoint import save_checkpoint

    t = _trainer()
    step = int(jax.device_get(t.state["step"]))
    out = save_checkpoint(path, jax.device_get(t.state), step)
    return json.dumps({"path": str(out)})


def load(path: str) -> str:
    from .train.checkpoint import latest_checkpoint, restore_checkpoint

    t = _trainer()
    ckpt = latest_checkpoint(path) or path
    host = jax.device_get(t.state)
    # place_state keeps the live shardings (TP model-axis shards included);
    # a bare replicate() here would silently de-shard a TP run.
    t.place_state(restore_checkpoint(ckpt, host))
    return json.dumps({"restored": str(ckpt)})
