"""Python side of the C ABI (native/tpu_abi.h).

A module-level singleton trainer driven by simple string-in/string-out
calls, so the embedded-CPython boundary stays trivial: the C driver sends
one JSON config at init and receives one JSON metrics line per call.
State (params, optimizer, compiled step) lives here — device-resident for
the life of the process, unlike the reference's per-call device round
trips (SURVEY.md §3.3).
"""

from __future__ import annotations

import json

import jax

_STATE: dict = {}


def init(config_json: str) -> str:
    from .cli import _select_device
    from .data.datasets import get_dataset, load_idx_dataset
    from .models.presets import get_model
    from .train.trainer import Trainer
    from .utils.config import Config
    from .utils.logging import MetricsLogger, get_logger

    cfg = Config.from_json(config_json)
    if not _select_device(cfg, get_logger()):
        raise RuntimeError(f"device {cfg.device!r} unavailable")
    if cfg.dataset == "idx":
        ds = load_idx_dataset(
            "idx", cfg.train_images, cfg.train_labels,
            cfg.test_images, cfg.test_labels,
        )
    else:
        ds = get_dataset(cfg.dataset, data_dir=cfg.data_dir)
    model = get_model(cfg.model, input_shape=ds.input_shape)
    trainer = Trainer(model, ds, cfg, metrics=MetricsLogger(echo=False))
    _STATE.update(trainer=trainer, cfg=cfg, epoch=0)
    return json.dumps({"ok": True, "model": model.name,
                       "n_params": model.num_params(trainer.state["params"])})


def _trainer():
    if "trainer" not in _STATE:
        raise RuntimeError("runtime_abi.init() not called")
    return _STATE["trainer"]


def train_epoch() -> str:
    """Run one epoch via Trainer.run_epoch (the same loop the Python CLI
    uses — one implementation, one shuffle stream); returns metrics JSON."""
    t = _trainer()
    metrics = t.run_epoch(_STATE["epoch"])
    _STATE["epoch"] += 1
    metrics["seconds"] = round(metrics["seconds"], 3)
    return json.dumps(metrics)


def evaluate() -> str:
    ntests, ncorrect = _trainer().evaluate()
    return json.dumps({"ntests": ntests, "ncorrect": ncorrect})


def save(path: str) -> str:
    from .train.checkpoint import save_checkpoint

    t = _trainer()
    step = int(jax.device_get(t.state["step"]))
    out = save_checkpoint(path, jax.device_get(t.state), step)
    return json.dumps({"path": str(out)})


def load(path: str) -> str:
    from .train.checkpoint import latest_checkpoint, restore_checkpoint

    t = _trainer()
    ckpt = latest_checkpoint(path) or path
    host = jax.device_get(t.state)
    # place_state keeps the live shardings (TP model-axis shards included);
    # a bare replicate() here would silently de-shard a TP run.
    t.place_state(restore_checkpoint(ckpt, host))
    return json.dumps({"restored": str(ckpt)})
