"""Sequence/context parallelism over a 'seq' mesh axis.

The reference has no sequence axis at all (SURVEY.md §5.7) — this module
is the long-context capability of the framework, built the TPU way on two
classic schedules:

- **Ring attention** (`ring_attention`): q/k/v sharded on S over 'seq'.
  Each device keeps its query shard; key/value shards rotate around the
  ring with `lax.ppermute` (ICI neighbor exchange), and each arriving
  block folds into the exact online-softmax state (ops/attention.py).
  P-1 rotate hops plus a final fold of the last-arrived block (the P-th
  rotate would only return each device its own block, so it is skipped):
  after the final fold every query has attended to every key — exact
  attention, O(S/P) memory per device, compute/comm overlapped by XLA
  across the fori_loop's ppermute + matmul.

- **Ulysses all-to-all** (`ulysses_attention`): q/k/v sharded on S; an
  all_to_all re-shards to heads-sharded/sequence-complete, each device
  runs FULL attention for its head subset, and a second all_to_all
  restores sequence sharding. Two collectives total; needs H % P == 0.

Both are pure SPMD bodies meant to be called INSIDE shard_map (see
`make_ring_attention` / `make_ulysses_attention` for the wrapped forms)
and are exact — tested to parity against the single-device oracle on the
8-device CPU mesh, gradients included (ppermute/all_to_all differentiate).

Causal masking works from global positions: shard s of P owns rows
[s*S/P, (s+1)*S/P), and the origin shard of a rotating k/v block is
recovered from the hop count, so masks are built per (my shard, their
shard) pair without materializing anything global.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..obs.trace import annotate
from ..utils.donation import donate_jit
from ..ops.attention import (
    NEG_INF,
    finalize_online,
    init_online,
    online_softmax_block,
    repeat_kv,
)

SEQ_AXIS = "seq"


def _pair_mask(my_shard, src_shard, s_local, causal: bool):
    """(s_local, s_local) mask for my query rows vs a block that
    originated on `src_shard`. True = attend."""
    if not causal:
        return jnp.ones((s_local, s_local), bool)
    qpos = my_shard * s_local + jnp.arange(s_local)[:, None]
    kpos = src_shard * s_local + jnp.arange(s_local)[None, :]
    return kpos <= qpos


def ring_attention(q, k, v, *, axis: str = SEQ_AXIS, causal: bool = False):
    """SPMD body: exact ring attention for one sequence shard.

    q, k, v: (B, s_local, H, D) — this device's shard of the sequence.
    Must run inside shard_map over a mesh with `axis`. Returns the local
    output shard (B, s_local, H, D).
    """
    p = lax.axis_size(axis)
    me = lax.axis_index(axis)
    s_local = q.shape[1]
    # Ring permutation: shard i hands its current k/v block to shard i+1,
    # so after h hops this device holds the block that started on me - h.
    perm = [(i, (i + 1) % p) for i in range(p)]

    def fold(o_m_l, kh, vh, h):
        src = (me - h) % p
        mask = _pair_mask(me, src, s_local, causal)
        # GQA: the ring rotates the SMALL (Hkv) buffers (less ICI
        # traffic); heads expand only at fold time, on-device.
        kh = repeat_kv(kh, q.shape[2])
        vh = repeat_kv(vh, q.shape[2])
        return online_softmax_block(o_m_l, q, kh, vh, mask)

    def hop(h, carry):
        o_m_l, kh, vh = carry
        with annotate("sp.ring.fold"):
            o_m_l = fold(o_m_l, kh, vh, h)
        with annotate("sp.ring.ppermute"):
            kh = lax.ppermute(kh, axis, perm)
            vh = lax.ppermute(vh, axis, perm)
        return o_m_l, kh, vh

    # p-1 fold+rotate hops, then fold the final resident block WITHOUT
    # rotating — the p-th ppermute would only hand every device back its
    # own k/v block, a wasted ICI hop per attention call.
    o_m_l, kh, vh = lax.fori_loop(0, p - 1, hop, (init_online(q), k, v))
    o_m_l = fold(o_m_l, kh, vh, p - 1)
    return finalize_online(o_m_l, q.dtype)


# ---------------------------------------------------------------------------
# Ring-FLASH attention: the fused Pallas flash kernel as the per-shard
# fold inside the ring. Same collective schedule as ring_attention, but
# each arriving k/v block is folded by ops/pallas_attention's fused
# kernels instead of the jnp online_softmax_block — logits never leave
# VMEM. Differentiable via a custom VJP whose backward is a second ring
# pass: k/v blocks rotate together with their dk/dv accumulators, and
# each hop reuses the fused flash backward for one (q-shard, k-block)
# pair with the probabilities reconstructed from the forward's global
# logsumexp. This is the form a real long-context trainer runs.
# ---------------------------------------------------------------------------


def _flash_block(q, k, v, causal_flag: bool):
    """(o, lse) of the fused flash forward for one k/v block; o stays in
    the kernel's f32 (out_f32 — no per-hop truncation to a bf16 input
    dtype before the f32 merge)."""
    from ..ops.pallas_attention import _flash_forward

    return _flash_forward(q, k, v, causal_flag, with_lse=True, out_f32=True)


def _merge_partials(o, lse, o_blk, lse_blk, b, h):
    """Fold a per-block normalized partial (o_blk, lse_blk) into the
    running (o, lse). Both o's are (B, S, H, D) f32, lse's (B*H, S).
    Standard two-softmax merge: weights exp(lse_i - logaddexp(...))."""
    lse_new = jnp.logaddexp(lse, lse_blk)
    w_old = jnp.exp(lse - lse_new)
    w_new = jnp.exp(lse_blk - lse_new)

    def to_bsh1(x):  # (B*H, S) -> (B, S, H, 1)
        return x.reshape(b, h, x.shape[-1]).transpose(0, 2, 1)[..., None]

    return o * to_bsh1(w_old) + o_blk * to_bsh1(w_new), lse_new


def _ring_case(me, src):
    """0 = block fully before my rows (attend all), 1 = my own block
    (local causal), 2 = block fully after (skip)."""
    return jnp.where(src == me, 1, jnp.where(src < me, 0, 2))


def _hop_dispatch(me, p, hcnt, causal, full, diag, none):
    """The per-hop mask dispatch shared by the forward fold and the
    backward contrib: the block folded at hop `hcnt` originated on
    src = (me - hcnt) % p, and with equal shards a (me, src) pair is
    either fully attended, the local-causal diagonal, or fully masked."""
    if not causal:
        return full(None)
    src = (me - hcnt) % p
    return lax.switch(_ring_case(me, src), (full, diag, none), None)


def _ring_flash_fwd_impl(q, k, v, axis, causal):
    p = lax.axis_size(axis)
    me = lax.axis_index(axis)
    b, s_local, h, d = q.shape
    perm = [(i, (i + 1) % p) for i in range(p)]

    def fold(o, lse, kh, vh, hcnt):
        o_blk, lse_blk = _hop_dispatch(
            me, p, hcnt, causal,
            full=lambda _: _flash_block(q, kh, vh, False),
            diag=lambda _: _flash_block(q, kh, vh, True),
            none=lambda _: (
                jnp.zeros((b, s_local, h, d), jnp.float32),
                jnp.full((b * h, s_local), NEG_INF, jnp.float32),
            ),
        )
        return _merge_partials(o, lse, o_blk, lse_blk, b, h)

    def hop(hcnt, carry):
        o, lse, kh, vh = carry
        with annotate("sp.ring_flash.fold"):
            o, lse = fold(o, lse, kh, vh, hcnt)
        with annotate("sp.ring_flash.ppermute"):
            kh = lax.ppermute(kh, axis, perm)
            vh = lax.ppermute(vh, axis, perm)
        return o, lse, kh, vh

    o0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    lse0 = jnp.full((b * h, s_local), NEG_INF, jnp.float32)
    o, lse, kh, vh = lax.fori_loop(0, p - 1, hop, (o0, lse0, k, v))
    o, lse = fold(o, lse, kh, vh, p - 1)
    return o.astype(q.dtype), lse


def _ring_flash_bwd_impl(q, k, v, o, lse, g, axis, causal):
    from ..ops.pallas_attention import _flash_backward

    p = lax.axis_size(axis)
    me = lax.axis_index(axis)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def contrib(kh, vh, hcnt):
        # grads_f32: each hop's partial stays f32 into the accumulators —
        # one final cast at the end, not p per-hop bf16 roundings.
        return _hop_dispatch(
            me, p, hcnt, causal,
            full=lambda _: _flash_backward(q, kh, vh, o, lse, g, False,
                                           grads_f32=True),
            diag=lambda _: _flash_backward(q, kh, vh, o, lse, g, True,
                                           grads_f32=True),
            none=lambda _: (
                jnp.zeros(q.shape, jnp.float32),
                jnp.zeros(kh.shape, jnp.float32),
                jnp.zeros(vh.shape, jnp.float32),
            ),
        )

    def hop(hcnt, carry):
        dq, kh, vh, dkh, dvh = carry
        dq_c, dk_c, dv_c = contrib(kh, vh, hcnt)
        dq = dq + dq_c
        dkh = dkh + dk_c
        dvh = dvh + dv_c
        # k/v rotate WITH their gradient accumulators so each dk/dv rides
        # along with its block; after p total rotations they are home.
        kh, vh, dkh, dvh = (
            lax.ppermute(t, axis, perm) for t in (kh, vh, dkh, dvh)
        )
        return dq, kh, vh, dkh, dvh

    zero = jnp.zeros(q.shape, jnp.float32)
    carry = (zero, k, v, jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32))
    dq, kh, vh, dkh, dvh = lax.fori_loop(0, p - 1, hop, carry)
    # Final hop: contribute, then rotate ONLY the accumulators home (the
    # k/v rotate would be the wasted return hop — see ring_attention).
    dq_c, dk_c, dv_c = contrib(kh, vh, p - 1)
    dq = dq + dq_c
    dkh = lax.ppermute(dkh + dk_c, axis, perm)
    dvh = lax.ppermute(dvh + dv_c, axis, perm)
    return dq.astype(q.dtype), dkh.astype(k.dtype), dvh.astype(v.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_flash(q, k, v, axis, causal):
    o, _ = _ring_flash_fwd_impl(q, k, v, axis, causal)
    return o


def _ring_flash_vjp_fwd(q, k, v, axis, causal):
    o, lse = _ring_flash_fwd_impl(q, k, v, axis, causal)
    return o, (q, k, v, o, lse)


def _ring_flash_vjp_bwd(axis, causal, res, g):
    q, k, v, o, lse = res
    return _ring_flash_bwd_impl(q, k, v, o, lse, g, axis, causal)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_flash_attention(q, k, v, *, axis: str = SEQ_AXIS, causal: bool = False):
    """SPMD body: ring attention with the fused flash kernel as the fold.

    q, k, v: (B, s_local, H, D), s_local a multiple of 128 (the flash
    kernel's block constraint). Must run inside shard_map over a mesh
    with `axis`. Exact (same online-softmax algebra as ring_attention),
    differentiable (fused flash backward per hop), O(s_local) VMEM.
    """
    return _ring_flash(q, k, v, axis, causal)


def ulysses_attention(q, k, v, *, axis: str = SEQ_AXIS, causal: bool = False):
    """SPMD body: all-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    q, k, v: (B, s_local, H, D) with H divisible by the axis size. The
    first all_to_all trades the local sequence dim for a head shard (each
    device ends up with the FULL sequence for H/P heads), full attention
    runs locally, and the inverse all_to_all restores sequence sharding.
    """
    from ..ops.attention import attention

    p = lax.axis_size(axis)
    h = q.shape[2]
    if h % p:
        raise ValueError(f"heads {h} not divisible by seq-axis size {p}")
    # GQA: Ulysses shards the HEAD dim, so expand kv to full H first
    # (costs the repeat in the all_to_all; ring keeps kv small —
    # prefer ring/ring_flash for GQA models).
    k = repeat_kv(k, h)
    v = repeat_kv(v, h)

    # Tiled all_to_all: split the head dim into P chunks, receive every
    # shard's chunk concatenated along the sequence dim -> each device
    # holds the FULL sequence for H/P heads. (The untiled form would need
    # reshapes whose transpose miscompiles under shard_map — tiled is also
    # simply the natural fit here.)
    def to_heads(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    with annotate("sp.ulysses.all_to_all_heads"):
        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    with annotate("sp.ulysses.attention"):
        out = attention(qh, kh, vh, causal=causal)
    with annotate("sp.ulysses.all_to_all_seq"):
        return to_seq(out)


def _wrap(body, mesh, axis):
    spec = P(None, axis)  # (B, S, H, D): shard the sequence dim

    @partial(jax.jit, static_argnames=("causal",))
    def fn(q, k, v, causal=False):
        return jax.shard_map(
            partial(body, axis=axis, causal=causal),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)

    return fn


def make_ring_attention(mesh, axis: str = SEQ_AXIS):
    """jitted (q, k, v, causal=False) -> out with S sharded over `axis`."""
    return _wrap(ring_attention, mesh, axis)


def make_ring_flash_attention(mesh, axis: str = SEQ_AXIS):
    """jitted (q, k, v, causal=False) -> out with S sharded over `axis`,
    folding each hop with the fused Pallas flash kernel."""
    return _wrap(ring_flash_attention, mesh, axis)


def make_ulysses_attention(mesh, axis: str = SEQ_AXIS):
    """jitted (q, k, v, causal=False) -> out with S sharded over `axis`."""
    return _wrap(ulysses_attention, mesh, axis)


# ---------------------------------------------------------------------------
# Sequence-parallel LM training
# ---------------------------------------------------------------------------


def make_sp_lm_train_step(
    model,
    optimizer,
    mesh,
    *,
    impl: str = "ring",
    axis: str = SEQ_AXIS,
    data_axis: str | None = None,
    donate: bool = True,
    remat: bool = False,
    moe_aux_weight: float = 0.01,
    compute_dtype=None,
    ce_chunk: int = 0,
    state_specs=None,
    grad_clip: float = 0.0,
    grad_accum: int = 1,
):
    """Jitted causal-LM train step with the sequence dim sharded on `axis`
    (long-context training: each device holds S/P tokens of activations)
    and, optionally, the batch dim sharded on `data_axis` (SP x DP).

    Params are replicated by default; tokens/targets are (B, S) int32
    sharded (data_axis, axis). Inside shard_map the model runs on its
    sequence shard — embeddings/LN/MLP are per-position, and attention
    is the ring or Ulysses body with absolute positions recovered from
    the axis index. Gradients/metrics pmean over every populated mesh
    axis (they are means over tokens, and shards are equal-sized).

    state_specs enables FSDP x SP (ZeRO x ring — the long-context
    memory pairing): pass the state's PartitionSpec tree (params sharded
    over `data_axis` on their largest dim, parallel/fsdp.fsdp_specs; the
    trainer derives it from the placed state). The step then all-gathers
    each data-sharded leaf over 'data' before use and one
    psum_scatter/n_data per gradient leaf is both the DP mean and the
    ZeRO reduce-scatter — master params + optimizer state stay sharded,
    exactly the pp.py FSDP pattern inside the SP shard_map. With
    state_specs, --grad-clip must clip IN-STEP (`grad_clip`): optax's
    clip would see a per-rank partial norm of the scattered grads.

    ce_chunk > 0 computes the shard-local loss with the fused chunked
    cross-entropy (ops/losses.chunked_ce_mean) — the natural pairing for
    long context, where even the SHARD-local (B, S/P, V) f32 logits are
    large; must divide the per-shard sequence S/P.

    grad_accum > 1 accumulates per-micro-batch gradients via dp.py's
    shared helper (interleaved split of the LOCAL batch dim, one
    micro-batch of activations live); the ring collectives run
    uniformly per micro-batch on every rank. Must divide the per-shard
    batch.

    Returns step(state, tokens, targets) -> (state, {"loss": ...}).
    """
    import optax

    fsdp = state_specs is not None
    if fsdp and not data_axis:
        raise ValueError("FSDP x SP shards params over 'data'; the mesh "
                         "needs a data axis of size > 1")
    if grad_clip > 0 and not fsdp:
        raise ValueError(
            "grad_clip is the FSDP x SP in-step clip (the scattered "
            "grads' norm is per-rank partial); with replicated params "
            "use the optax clip_by_global_norm transform instead"
        )
    pspecs = state_specs["params"] if fsdp else None
    n_data = mesh.shape.get(data_axis, 1) if data_axis else 1

    def _data_dim(spec) -> int | None:
        return (tuple(spec).index(data_axis)
                if data_axis in tuple(spec) else None)

    if impl == "ring":
        attn_body = ring_attention
    elif impl == "ring_flash":
        attn_body = ring_flash_attention
    elif impl == "ulysses":
        attn_body = ulysses_attention
    else:
        raise ValueError(
            f"unknown SP impl {impl!r}; 'ring', 'ring_flash' or 'ulysses'"
        )
    reduce_axes = tuple(a for a in (data_axis, axis) if a)

    n_seq = mesh.shape[axis]

    def step(state, tokens, targets):
        s_local = tokens.shape[1]
        if s_local * n_seq > model.max_seq:
            # apply() can only see the local shard length; enforce the
            # GLOBAL bound here so pos_offset can't push positions past
            # the embedding table (which would silently clamp).
            raise ValueError(
                f"global sequence {s_local * n_seq} exceeds "
                f"max_seq {model.max_seq}"
            )
        if impl == "ring_flash" and s_local % 128:
            # Fail here with global context — the kernel's own check
            # would name only the confusing shard-local length.
            raise ValueError(
                f"impl='ring_flash' needs the per-shard sequence to be a"
                f" multiple of 128 (flash block granularity): global"
                f" S={s_local * n_seq} over {axis}={n_seq} devices gives"
                f" s_local={s_local}"
            )
        pos_offset = lax.axis_index(axis) * s_local
        attn = partial(attn_body, axis=axis, causal=True)

        if ce_chunk and s_local % ce_chunk:
            raise ValueError(
                f"ce_chunk {ce_chunk} must divide the per-shard sequence "
                f"{s_local} (global S={s_local * n_seq} over {axis}="
                f"{n_seq})"
            )

        def loss_fn(params, tokens, targets):
            # MoE blocks (if the model has any) run expert-parallel over
            # the SAME 'seq' axis the sequence is sharded on (EP x SP:
            # each device holds E/P experts AND S/P tokens;
            # parallel/ep.py's all_to_alls route between them). Dense
            # models return aux = 0.
            if ce_chunk:
                from ..ops.losses import chunked_ce_mean

                feats, aux = model.apply(
                    params, tokens, attn_fn=attn, pos_offset=pos_offset,
                    remat=remat, moe_axis=axis, return_aux=True,
                    compute_dtype=compute_dtype, return_features=True,
                )
                nll = chunked_ce_mean(
                    feats, params["head"], targets, ce_chunk, compute_dtype
                )
                return nll + moe_aux_weight * aux
            logits, aux = model.apply(
                params, tokens, attn_fn=attn, pos_offset=pos_offset,
                remat=remat, moe_axis=axis, return_aux=True,
                compute_dtype=compute_dtype,
            )
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
            return jnp.mean(nll) + moe_aux_weight * aux

        # dp.py's shared accumulation (interleaved micro-split, one
        # micro-batch of activations live); the ring/all-to-all
        # collectives run uniformly per micro-batch on every rank, so
        # accumulation inside shard_map is safe.
        if grad_accum > 1 and tokens.shape[0] % grad_accum:
            raise ValueError(
                f"per-shard batch {tokens.shape[0]} not divisible by "
                f"grad_accum {grad_accum}"
            )
        from .dp import local_grads_no_aux

        def grads_of(p, tk, tg):
            return local_grads_no_aux(loss_fn, p, tk, tg, grad_accum)

        if fsdp:
            # Gather the full weights transiently; differentiate w.r.t.
            # the FULL tree so each gradient leaf is full-width before
            # the scatter.
            full = jax.tree.map(
                lambda p, s: (
                    lax.all_gather(p, data_axis, axis=_data_dim(s),
                                   tiled=True)
                    if _data_dim(s) is not None else p
                ),
                state["params"], pspecs,
                is_leaf=lambda x: isinstance(x, P),
            )
            loss, grads = grads_of(full, tokens, targets)
            # Sharded leaves: psum_scatter/n = DP mean + ZeRO scatter
            # back to this rank's slice. Replicated leaves: plain pmean.
            # Everything then pmeans over 'seq' (equal shards).
            grads = jax.tree.map(
                lambda g, s: lax.pmean(
                    lax.psum_scatter(
                        g, data_axis, scatter_dimension=_data_dim(s),
                        tiled=True,
                    ) / n_data
                    if _data_dim(s) is not None
                    else lax.pmean(g, data_axis),
                    axis,
                ),
                grads, pspecs,
                is_leaf=lambda x: isinstance(x, P),
            )
            loss = lax.pmean(loss, reduce_axes)
            if grad_clip > 0:
                # Scattered slices are disjoint over 'data' (psum);
                # replicated leaves are identical everywhere after the
                # pmeans (count once). Both the classification and the
                # clip application live in the shared helpers.
                from ..train.optimizer import (
                    clip_grads_by_global_sq,
                    split_grad_sq,
                )

                sliced, rep = split_grad_sq(grads, pspecs, data_axis)
                gn2 = lax.psum(sliced, data_axis) + rep
                grads = clip_grads_by_global_sq(grads, gn2, grad_clip)
        else:
            loss, grads = grads_of(state["params"], tokens, targets)
            grads = lax.pmean(grads, reduce_axes)
            loss = lax.pmean(loss, reduce_axes)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return (
            {"params": params, "opt_state": opt_state, "step": state["step"] + 1},
            {"loss": loss},
        )

    batch_spec = P(data_axis, axis)
    sspec = state_specs if fsdp else P()
    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(sspec, batch_spec, batch_spec),
        out_specs=(sspec, P()),
        check_vma=False,
    )
    return donate_jit(sharded, donate=donate)
