"""Multi-host runtime initialization.

The reference's process management is `mpirun -np 8` + MPI_Init
(Makefile:44, cnnmpi.c:419). The JAX equivalent for multi-host TPU pods is
`jax.distributed.initialize()`: each host process joins the same runtime,
`jax.devices()` becomes the global device list, and XLA routes collectives
over ICI within a slice and DCN across slices — user training code is
unchanged (SURVEY.md §5.8).

On a single host (this environment, and the reference's own test setup)
initialization is a no-op.
"""

from __future__ import annotations

import dataclasses

import jax

from ..utils.logging import get_logger


@dataclasses.dataclass(frozen=True)
class ProcessInfo:
    process_index: int
    process_count: int
    local_devices: int
    global_devices: int


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> ProcessInfo:
    """Join the multi-host runtime when launched as one process per host.

    With no arguments, relies on the TPU environment's auto-detection
    (e.g. GCE metadata) and silently stays single-process elsewhere —
    so the same entry point covers laptop CPU, one TPU VM, and a pod.
    """
    log = get_logger()
    if coordinator_address is not None or _looks_multiprocess():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        except Exception as e:  # already initialized or single-process env
            log.debug("jax.distributed.initialize skipped: %s", e)
    return process_info()


def _looks_multiprocess() -> bool:
    import os

    return any(k in os.environ for k in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS"))


def process_info() -> ProcessInfo:
    return ProcessInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_devices=jax.local_device_count(),
        global_devices=jax.device_count(),
    )


def barrier(name: str) -> None:
    """Block until every process reaches this point (the multihost
    checkpoint-write ordering fence: process 0 writes, everyone meets
    here, so no process can act on "the checkpoint exists" before it
    does — train/checkpoint.save_checkpoint). Single-process runs
    return immediately; `name` keys the rendezvous so two different
    barrier sites can't accidentally pair up."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
