"""Width-invariant data parallelism — the numerics behind elastic resume.

The problem (ISSUE 5): a preempted run must resume on whatever data-axis
width the scheduler hands back (dp=4 -> dp=2 -> dp=8), and the elastic
contract we prove is BITWISE — the resumed trajectory equals the
uninterrupted one. The standard DP step cannot give that: each device
takes the mean gradient of its local shard and `pmean`s the results, so
changing the width regroups the floating-point reductions (a 16-sample
local mean is not bitwise the sum of two 8-sample means) and the
trajectories drift apart within one step (measured ~1e-8/step on this
container's CPU backend — see tests/test_elastic.py).

The fix is to make the reduction tree a function of the CONFIG, not the
hardware: a fixed "elastic width" W0 defines B/W0-sample *canonical
microbatches*, and the step always computes

    grad = (1/W0) * balanced-binary-tree-sum of per-microbatch mean grads

no matter how many devices execute it. Each device scans its contiguous
W0/n microbatches (same per-microbatch program at every width — the
shapes are fixed by W0, not n), reduces them with the LOW levels of the
global balanced tree (reshape-halving: adjacent pairs, then pairs of
pairs), and a recursive-doubling ppermute all-reduce supplies the HIGH
levels (rank r adds rank r^1, then r^2, then r^4 — the same balanced
tree, and IEEE addition is commutative so every rank converges to
identical bits). Because each device's microbatches are an ALIGNED
contiguous block of a power-of-two size, its local subtree is exactly a
complete subtree of the global one — the total association is identical
for every power-of-two width n with W0/n >= 2.

Two compiler effects have to be fenced, both found empirically (this
container's XLA CPU; the guards are cheap everywhere):

- trip-count-1 loops are fully unrolled and re-fused with their
  surroundings, changing the microbatch computation's rounding — hence
  the W0 >= 2*n floor (every width keeps a real loop);
- the optimizer's multiply-add chains fuse differently depending on the
  gradient-producing program feeding them — an `optimization_barrier`
  around the scan body and between the reduced gradient and the
  optimizer pins both (without it, AdamW's moments drift ~1e-9/step
  across widths even on identical gradients).

Cost: the scan stacks W0/n per-microbatch gradient trees before the
tree reduce, so peak gradient memory is (W0/n)x the plain step's, and
per-microbatch kernels are smaller than full-shard ones. That is the
price of the bitwise contract; runs that don't need elasticity leave
`--elastic-width 0` and keep the plain pmean step.
"""

from __future__ import annotations

from collections.abc import Callable

import jax
import jax.numpy as jnp

from .mesh import DATA_AXIS


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def check_elastic_width(elastic_width: int, batch_size: int,
                        n_data: int) -> None:
    """Validate the (W0, batch, width) triple, raising ValueError with
    the constraint that failed. The rules exist for bitwise-ness, so
    they are hard errors, not clamps: W0 and the data-axis size must be
    powers of two (the balanced tree needs complete subtrees), W0 must
    divide the batch (fixed canonical microbatch size), and every
    width must keep >= 2 microbatches per device (XLA unrolls
    trip-count-1 loops and re-fuses the body — the one case measured to
    break bitwise equality)."""
    if not _is_pow2(elastic_width):
        raise ValueError(
            f"--elastic-width {elastic_width} must be a power of two "
            "(the width-invariant reduction is a balanced binary tree)"
        )
    if batch_size % elastic_width:
        raise ValueError(
            f"--elastic-width {elastic_width} must divide batch_size "
            f"{batch_size} (it fixes the canonical microbatch size)"
        )
    if not _is_pow2(n_data):
        raise ValueError(
            f"--elastic-width needs a power-of-two data-axis size "
            f"(got {n_data}): device blocks must be complete subtrees "
            "of the canonical reduction tree"
        )
    if elastic_width < 2 * n_data:
        raise ValueError(
            f"--elastic-width {elastic_width} must be >= 2x the "
            f"data-axis size ({n_data}): each device needs >= 2 "
            "canonical microbatches (a trip-count-1 scan is unrolled "
            "and re-fused by XLA, breaking the bitwise contract)"
        )


def local_tree_reduce(stacked):
    """Balanced binary tree sum over the leading axis (a power of two):
    adjacent pairs first, then pairs of pairs — the LOW levels of the
    global canonical tree. Explicit pairwise adds (r[:,0] + r[:,1]), so
    the association is pinned in the HLO graph rather than left to a
    reduce op's implementation-chosen order."""

    def halve(t):
        r = t.reshape(t.shape[0] // 2, 2, *t.shape[1:])
        return r[:, 0] + r[:, 1]

    n = jax.tree.leaves(stacked)[0].shape[0]
    while n > 1:
        stacked = jax.tree.map(halve, stacked)
        n //= 2
    return jax.tree.map(lambda t: t[0], stacked)


def tree_allreduce(tree, axis: str, n: int):
    """Recursive-doubling all-reduce over mesh axis `axis` (size `n`, a
    power of two) via ppermute: round r adds the partner at XOR-distance
    2^r, so rank 0 accumulates ((x0+x1)+(x2+x3))+... — the HIGH levels
    of the canonical balanced tree — and every rank converges to the
    SAME bits (IEEE addition is commutative, so partner-order mirroring
    cancels). n == 1 is the identity."""
    dist = 1
    while dist < n:
        perm = [(i, i ^ dist) for i in range(n)]
        tree = jax.tree.map(
            lambda t: t + jax.lax.ppermute(t, axis, perm), tree
        )
        dist *= 2
    return tree


def elastic_grads(
    grad_fn: Callable,
    x,
    y,
    *,
    elastic_width: int,
    axis: str = DATA_AXIS,
    axis_size: int = 1,
    prepare: Callable | None = None,
):
    """Width-invariant (loss, aux, grads) over the local batch shard.

    `grad_fn(px, py) -> (loss, aux, grads)` computes one canonical
    microbatch (params closed over — keeps the scan carry empty so the
    stacked ys are the only growth). `prepare(px, py, shard_index)`
    optionally transforms a microbatch first with its GLOBAL canonical
    index (0..W0) — augmentation must key on the canonical shard, not
    the device rank, or the pixel stream would change with the width.

    Every (loss, aux, grad) triple is reduced with the SAME canonical
    tree and divided by W0, so loss/aux come back as the mean over
    canonical microbatches — width-invariant like the grads (the plain
    step's pmean-of-shard-means equals this only in exact arithmetic).
    The scan body and the reduced outputs are optimization_barrier'd:
    the per-microbatch program and the downstream optimizer fusion must
    not vary with what surrounds them (module docstring).
    """
    k = elastic_width // axis_size  # canonical microbatches per device
    mb = x.shape[0] // k

    def split(t):
        return t.reshape(k, mb, *t.shape[1:])

    xs, ys = split(x), split(y)
    if prepare is not None:
        base = jax.lax.axis_index(axis) * k

    def body(i, xy):
        px, py = jax.lax.optimization_barrier(xy)
        if prepare is not None:
            px, py = prepare(px, py, base + i)
        out = grad_fn(px, py)
        return i + 1, jax.lax.optimization_barrier(out)

    _, stacked = jax.lax.scan(body, jnp.zeros((), jnp.int32), (xs, ys))
    reduced = tree_allreduce(local_tree_reduce(stacked), axis, axis_size)
    reduced = jax.tree.map(lambda t: t / elastic_width, reduced)
    return jax.lax.optimization_barrier(reduced)


def host_shard_rows(batch_size: int, process_index: int,
                    process_count: int) -> tuple[int, int]:
    """[start, stop) rows of the GLOBAL batch owned by this host — pure
    function of (batch index layout, process), never a stored per-rank
    cursor (ISSUE 5 data-order elasticity): a run resumed on a
    different host count re-derives its shard from the same global
    batch sequence, so the consumed data stream is identical. Row
    blocks are contiguous and equal-sized, matching the mesh's
    process-major device order.

    This is the CONTRACT for a future multihost data loader, pinned by
    tests; today's trainers feed global arrays in a single process and
    do not consume it yet (README "Data-order elasticity")."""
    if batch_size % process_count:
        raise ValueError(
            f"batch_size {batch_size} not divisible by process_count "
            f"{process_count}"
        )
    per = batch_size // process_count
    return process_index * per, (process_index + 1) * per
