"""Pipeline parallelism for the transformer LM over a 'pipe' mesh axis.

The CNN pipeline (parallel/pp.py) packs HETEROGENEOUS stages into padded
flat rows and switches on the stage index. The transformer needs none of
that machinery: its blocks are UNIFORM pytrees, so

- the L block params stack into leading-dim-L arrays (`stack_blocks`)
  whose leading dim shards over 'pipe' — each device holds L/P
  consecutive blocks and `lax.scan`s the SAME block computation
  (models/transformer.py apply_block — one implementation of the block
  math for every layout) over its local slice; no lax.switch, no
  padding;
- the embedding and final-LN/head are replicated: stage 0 embeds each
  microbatch as it enters the pipe, the LAST stage applies
  ln_f + head + causal-LM cross-entropy as microbatches drain; their
  gradients arrive stage-local and one psum over 'pipe' restores the
  full value (every other stage contributes zero);
- one jitted shard_map runs the GPipe schedule: lax.scan over
  M + P - 1 ticks, each tick runs the local stage then hands its
  activations to the next stage with lax.ppermute (ICI-neighbor
  transfer); `jax.grad` differentiates the schedule and the ppermute
  transpose IS the backward pipeline, exactly as in pp.py;
- composes with DP on a ('pipe', 'data') mesh: the microbatch dim
  shards over 'data', gradients pmean over 'data'.

MoE blocks compose too: each stage's blocks dispatch locally (experts
replicated within the stage, tokens routed per data shard) and the
balance loss is accumulated ONLY on a stage's valid ticks — a bubble
tick runs garbage activations through the router, so its statistics are
masked out of the gradient. Reference point: the reference has neither
pipelining nor a transformer (SURVEY.md §2 "PP: absent"; §5.7).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerLM, _layernorm
from .mesh import DATA_AXIS, PIPE_AXIS

# The batch-placement contract is IDENTICAL to the CNN pipeline's —
# one implementation, re-exported (parallel/pp.py).
from .pp import _batch_spec
from .pp import microbatch as pp_lm_microbatch  # noqa: F401
from .pp import pp_shard_batch as pp_lm_shard_batch  # noqa: F401
from ..utils.donation import donate_jit

TrainState = dict[str, Any]


def stack_blocks(params: dict) -> dict:
    """{'blocks': [L dicts], ...rest} -> {'blocks': stacked (L, ...),
    'rest': {...}} — the packed form whose block dim shards over 'pipe'."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params["blocks"])
    rest = {k: v for k, v in params.items() if k != "blocks"}
    return {"blocks": stacked, "rest": rest}


def unstack_blocks(packed: dict, depth: int) -> dict:
    """Inverse of stack_blocks — the standard params tree (for eval,
    decode, and parity against the unpipelined model)."""
    blocks = [
        jax.tree.map(lambda a: a[i], packed["blocks"]) for i in range(depth)
    ]
    return {**packed["rest"], "blocks": blocks}


def _state_specs(state):
    """PartitionSpecs by PATH: any leaf under a 'blocks' key shards its
    leading (block) dim over 'pipe'; everything else replicates. Path
    matching (not shape matching) — a depth-64 model with dim 64 must
    not confuse a (64, d) embedding row count for the block dim."""

    def spec(path, leaf):
        under_blocks = any(
            str(getattr(p, "key", getattr(p, "name", ""))) == "blocks"
            for p in path
        )
        if under_blocks and getattr(leaf, "ndim", 0) >= 1:
            return P(PIPE_AXIS, *([None] * (leaf.ndim - 1)))
        return P()

    leaves, treedef = jax.tree_util.tree_flatten_with_path(state)
    return jax.tree_util.tree_unflatten(
        treedef, [spec(path, leaf) for path, leaf in leaves]
    )


def _check_pp_lm(model: TransformerLM, n_pipe: int) -> None:
    if model.depth % n_pipe:
        raise ValueError(
            f"depth {model.depth} not divisible by pipe-axis size {n_pipe}"
        )


def make_pp_lm_state(model: TransformerLM, params, optimizer, mesh
                     ) -> TrainState:
    """Pack + place: stacked blocks on their pipe coordinate, the rest
    replicated; optimizer state created FROM the packed tree inherits the
    shardings leaf-for-leaf."""
    _check_pp_lm(model, mesh.shape[PIPE_AXIS])
    packed = stack_blocks(params)
    state = {
        "params": packed,
        "opt_state": optimizer.init(packed),
        "step": jnp.zeros((), jnp.int32),
    }
    specs = _state_specs(state)
    return jax.device_put(
        state,
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )




def make_gpipe_local_loss(model, *, M: int, n_pipe: int, compute_dtype,
                          remat: bool, ce_chunk: int, stage_body,
                          moe_aux_weight: float = 0.01,
                          seq_axis: str | None = None, n_seq: int = 1):
    """The GPipe schedule, shared by the plain pipelined step (below)
    and the TP x PP step (parallel/tp_pp_lm.py) — ONE implementation of
    the embed / tick / ppermute / drain machinery, parameterized by
    `stage_body(local_blocks, x, pos) -> (x, aux)` (the only thing the
    two meshes disagree on: a plain apply_block scan vs the Megatron
    block on the local head slice; aux is the stage's summed MoE
    balance loss, 0 for dense blocks).

    Returns local_loss(packed, toks_mb, tgt_mb) -> masked mean NLL plus
    the aux term — the NLL is nonzero only on the last stage's drained
    ticks, the aux only on each stage's VALID ticks (a bubble tick runs
    garbage activations through the router; its balance loss must not
    reach the gradient) — callers psum it over 'pipe'. MoE aux is
    per-microbatch (averaged over M), the same estimator every
    microbatched/sharded trainer uses: the Switch loss is a mean-of-
    products over tokens, so it only equals the serial full-batch value
    at M=1 (pinned by the parity test).

    seq_axis/n_seq put the schedule under SEQUENCE parallelism too
    (SP x PP): each device's buffers hold the (mb, S/n_seq, d) local
    shard, positions carry the shard's absolute offset, and the stage
    body runs ring attention over `seq_axis` — the ppermute pipeline
    handoff and the drain are per-seq-rank local, so nothing else
    changes. The caller pmeans loss/grads over 'seq' (equal shards).
    """
    cd = compute_dtype

    def local_loss(packed, toks_mb, tgt_mb):
        blocks = packed["blocks"]      # local (L/P, ...)
        rest = packed["rest"]
        mb, s = toks_mb.shape[1], toks_mb.shape[2]
        if s * n_seq > model.max_seq:
            # Trace-time check (shapes are static): XLA's gather would
            # silently clamp positions past the pos_emb table — the same
            # loud failure apply() raises (models/transformer.py), which
            # this schedule bypasses. Under SP, s is the LOCAL shard;
            # the bound is on the global sequence.
            raise ValueError(
                f"sequence length {s * n_seq} exceeds max_seq "
                f"{model.max_seq}"
            )
        pos = jnp.arange(s)
        if seq_axis is not None:
            pos = lax.axis_index(seq_axis) * s + pos
        s_idx = lax.axis_index(PIPE_AXIS)
        fwd_perm = [(i, (i + 1) % n_pipe) for i in range(n_pipe)]
        w = (lambda t: t.astype(cd)) if cd else (lambda t: t)

        def embed(tok):
            x = rest["tok_emb"][tok]
            if model.pos == "learned":
                x = x + rest["pos_emb"][pos][None, :, :]
            return w(x)

        stage = lambda x: stage_body(blocks, x, pos)
        if remat:
            stage = jax.checkpoint(stage)

        def drain_nll(y, tgt):
            feats = _layernorm(y, rest["ln_f"]["g"], rest["ln_f"]["b"])
            if ce_chunk:
                from ..ops.losses import chunked_ce_mean

                return chunked_ce_mean(feats, rest["head"], tgt,
                                       ce_chunk, cd)
            logits = jnp.matmul(
                feats, w(rest["head"]), preferred_element_type=jnp.float32
            )
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
            return jnp.mean(nll)

        def tick(carry, t):
            buf, nll_sum, aux_sum = carry
            # lax.cond, not jnp.where: only stage 0 pays the embedding
            # gather and only the LAST stage's drained ticks pay the
            # head matmul + log_softmax (the largest matmul in the
            # model) — a where() would run them on every stage at every
            # tick, P*(M+P-1) times instead of M. No collectives inside
            # either branch (under TP x PP the model ranks run the
            # branches identically on replicated activations), so the
            # per-device divergence is safe.
            inp = lax.cond(
                s_idx == 0,
                lambda: embed(toks_mb[jnp.minimum(t, M - 1)]),
                lambda: buf,
            )
            y, aux = stage(inp)
            # Stage s processes microbatch t - s at tick t; anything
            # else is a bubble whose router statistics are garbage.
            valid = (t - s_idx >= 0) & (t - s_idx < M)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
            out_t = t - (n_pipe - 1)
            drained = (s_idx == n_pipe - 1) & (out_t >= 0) & (out_t < M)
            nll = lax.cond(
                drained,
                lambda: drain_nll(y, tgt_mb[jnp.clip(out_t, 0, M - 1)]),
                lambda: jnp.float32(0),
            )
            return (lax.ppermute(y, PIPE_AXIS, fwd_perm),
                    nll_sum + nll, aux_sum), None

        buf0 = jnp.zeros(
            (mb, s, model.dim), cd if cd else jnp.float32
        )
        (_, nll_sum, aux_sum), _ = lax.scan(
            tick, (buf0, jnp.float32(0), jnp.float32(0)),
            jnp.arange(M + n_pipe - 1)
        )
        # Per-microbatch means averaged over microbatches == the global
        # mean NLL (equal microbatch sizes). Masked: the NLL only on the
        # last stage's drained ticks, the aux on every stage's valid
        # ticks — the caller's psum over 'pipe' assembles both. The raw
        # aux mean rides along as the has_aux extra so a TP caller
        # (whose moe_aux_weight is 1/n_tp-scaled for gradient
        # correctness) can repair the metric value.
        return (nll_sum + moe_aux_weight * aux_sum) / M, aux_sum / M

    return local_loss


def sp_pp_batch_spec(mesh) -> P:
    """The (M, mb, S) batch PartitionSpec when the mesh has a 'seq'
    axis: microbatches over 'data' (when present), positions over
    'seq'. ONE definition consumed by the placement helper below AND by
    every seq-carrying pipelined step's shard_map in_specs (here and
    tp_pp_lm.py) — the two sides of the contract cannot drift."""
    from .sp import SEQ_AXIS

    return P(None, DATA_AXIS if DATA_AXIS in mesh.axis_names else None,
             SEQ_AXIS)


def sp_pp_shard_batch(t, mesh):
    """Place (M, mb, S) microbatched int32 tokens for the SP x PP step."""
    from jax.sharding import NamedSharding

    return jax.device_put(t, NamedSharding(mesh, sp_pp_batch_spec(mesh)))


def _jit_pp_step(optimizer, local_loss, state, mesh, *, reduce_axes,
                 grad_clip, donate, bspec):
    """The pipelined step assembly shared by the plain PP and SP x PP
    makers (tp_pp_lm.py has its own — the 'model' axis changes the norm
    classification): psum the masked loss and the rest-tree gradients
    over 'pipe', pmean everything over `reduce_axes` (the axes whose
    shards hold different tokens — ('data'?) plain, ('seq'[, 'data'])
    under SP), in-step cross-rank clip (block rows disjoint over 'pipe',
    the repaired rest once), optimizer update, shard_map + jit."""

    def step(state, toks_mb, tgt_mb):
        (loss, _aux), grads = jax.value_and_grad(
            local_loss, has_aux=True
        )(state["params"], toks_mb, tgt_mb)
        # (aux is already inside `loss` at full weight here — the
        # has_aux extra only matters to the TP x PP caller's metric.)
        # Block grads are stage-local (each device owns its blocks); the
        # replicated leaves (embedding, ln_f, head) received only their
        # OWN stage's contribution — zero everywhere but the stage that
        # uses them — so one psum over 'pipe' restores the full gradient.
        grads = {
            "blocks": grads["blocks"],
            "rest": jax.tree.map(
                lambda g: lax.psum(g, PIPE_AXIS), grads["rest"]
            ),
        }
        loss = lax.psum(loss, PIPE_AXIS)
        if reduce_axes:
            grads = jax.tree.map(
                lambda g: lax.pmean(g, reduce_axes), grads
            )
            loss = lax.pmean(loss, reduce_axes)
        if grad_clip > 0:
            # Cross-stage global norm, each logical parameter once: the
            # block slices are DISJOINT over 'pipe' (psum their squared
            # norms), the psum-repaired rest is identical on every stage
            # (count once) — and after the pmeans everything is
            # replicated across reduce_axes. The scale comes out
            # identical on every rank; the semantics live in the shared
            # helpers.
            from ..train.optimizer import clip_grads_by_global_sq, grad_sq

            gn2 = lax.psum(grad_sq(grads["blocks"]), PIPE_AXIS) \
                + grad_sq(grads["rest"])
            grads = clip_grads_by_global_sq(grads, gn2, grad_clip)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return (
            {"params": params, "opt_state": opt_state,
             "step": state["step"] + 1},
            {"loss": loss},
        )

    specs = _state_specs(state)
    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(specs, bspec, bspec),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return donate_jit(sharded, donate=donate)


def make_sp_pp_lm_train_step(
    model: TransformerLM,
    optimizer: optax.GradientTransformation,
    mesh,
    state: TrainState,
    *,
    num_microbatches: int | None = None,
    compute_dtype=None,
    remat: bool = False,
    donate: bool = True,
    grad_clip: float = 0.0,
    impl: str = "ring",
    ce_chunk: int = 0,
    moe_aux_weight: float = 0.01,
):
    """Jitted GPipe x ring-attention train step over a ('pipe', 'seq'
    [, 'data']) mesh — long sequences THROUGH a pipelined model: blocks
    stage-sharded over 'pipe' (make_pp_lm_state, unchanged — 'seq'
    never shards parameters), positions sharded over 'seq', each
    stage's attention the ring (or ring-flash fold) on its local shard.
    The schedule is the shared make_gpipe_local_loss with a seq offset;
    loss/grads additionally pmean over ('seq'[, 'data']) exactly as in
    the plain SP step (parallel/sp.py). MoE blocks ride along
    expert-parallel over the SAME 'seq' axis (EP x SP inside each
    stage), aux masked on bubble ticks as in the plain pipelined step.

    step(state, toks_mb, tgt_mb) -> (state, {"loss": ...}); toks/tgt
    (M, mb, S) int32 placed via sp_pp_shard_batch.
    """
    from .sp import SEQ_AXIS, ring_attention, ring_flash_attention

    n_pipe = mesh.shape[PIPE_AXIS]
    n_seq = mesh.shape[SEQ_AXIS]
    _check_pp_lm(model, n_pipe)
    has_data = DATA_AXIS in mesh.axis_names
    M = num_microbatches or n_pipe
    cd = compute_dtype
    reduce_axes = (SEQ_AXIS, DATA_AXIS) if has_data else (SEQ_AXIS,)

    if impl == "ring":
        attn_body = ring_attention
    elif impl == "ring_flash":
        attn_body = ring_flash_attention
    else:
        raise ValueError(
            f"unknown SP x PP impl {impl!r}; 'ring' or 'ring_flash' "
            "(each stage's attention is the sequence-sharded ring)"
        )

    def attn(q, k, v):
        if impl == "ring_flash" and q.shape[1] % 128:
            # Fail with GLOBAL context — the kernel's own check would
            # name only the confusing shard-local length (same guard as
            # the plain SP step, parallel/sp.py).
            raise ValueError(
                f"impl='ring_flash' needs the per-shard sequence to be a"
                f" multiple of 128 (flash block granularity): global"
                f" S={q.shape[1] * n_seq} over seq={n_seq} devices gives"
                f" s_local={q.shape[1]}"
            )
        return attn_body(q, k, v, axis=SEQ_AXIS, causal=True)

    def stage_body(blocks, x, pos):
        def body(carry, blk):
            x, aux = carry
            x, a = model.apply_block(
                blk, x, pos=pos, attn=attn, compute_dtype=cd,
                moe_axis=SEQ_AXIS,
            )
            return (x, aux + a), None

        (x, aux), _ = lax.scan(body, (x, jnp.float32(0)), blocks)
        return x, aux

    local_loss = make_gpipe_local_loss(
        model, M=M, n_pipe=n_pipe, compute_dtype=cd, remat=remat,
        ce_chunk=ce_chunk, stage_body=stage_body,
        moe_aux_weight=moe_aux_weight, seq_axis=SEQ_AXIS, n_seq=n_seq,
    )
    return _jit_pp_step(
        optimizer, local_loss, state, mesh, reduce_axes=reduce_axes,
        grad_clip=grad_clip, donate=donate, bspec=sp_pp_batch_spec(mesh),
    )


def make_pp_lm_train_step(
    model: TransformerLM,
    optimizer: optax.GradientTransformation,
    mesh,
    state: TrainState,
    *,
    num_microbatches: int | None = None,
    compute_dtype=None,
    remat: bool = False,
    donate: bool = True,
    grad_clip: float = 0.0,
    attn_impl: str = "oracle",
    ce_chunk: int = 0,
    moe_aux_weight: float = 0.01,
):
    """Jitted GPipe train step for the LM (state from make_pp_lm_state —
    its structure supplies the shard_map specs, as in pp.py).

    step(state, toks_mb, tgt_mb) -> (state, {"loss": ...}); toks/tgt are
    (M, mb, S) int32 placed via pp_lm_shard_batch. Each stage sees the
    UNSHARDED sequence (PP shards blocks and microbatches, not positions),
    so the plain fused flash kernel drops straight in: `attn_impl`
    routes "flash" to the Pallas pair, "oracle" to the quadratic jnp
    reference — no ring machinery needed (that is SP's job). `ce_chunk`
    fuses the last stage's drain head-matmul into the chunked CE
    (ops/losses.py chunked_ce_mean), so the (mb, S, V) f32 logits are
    never materialized per drained microbatch — PP exists for big
    models, which is exactly where the logits bill binds.
    """
    n_pipe = mesh.shape[PIPE_AXIS]
    _check_pp_lm(model, n_pipe)
    has_data = DATA_AXIS in mesh.axis_names
    M = num_microbatches or n_pipe
    cd = compute_dtype

    from ..train.lm import get_attn_fn

    attn = get_attn_fn(attn_impl)

    def stage_body(blocks, x, pos):
        def body(carry, blk):
            x, aux = carry
            x, a = model.apply_block(
                blk, x, pos=pos, attn=attn, compute_dtype=cd
            )
            return (x, aux + a), None

        (x, aux), _ = lax.scan(body, (x, jnp.float32(0)), blocks)
        return x, aux

    local_loss = make_gpipe_local_loss(
        model, M=M, n_pipe=n_pipe, compute_dtype=cd, remat=remat,
        ce_chunk=ce_chunk, stage_body=stage_body,
        moe_aux_weight=moe_aux_weight,
    )
    return _jit_pp_step(
        optimizer, local_loss, state, mesh,
        reduce_axes=(DATA_AXIS,) if has_data else (),
        grad_clip=grad_clip, donate=donate, bspec=_batch_spec(mesh),
    )
