"""Parallelism: device meshes, SPMD data parallelism, multi-host init.

This package replaces the reference's entire distributed layer — raw MPI
calls inlined in main() (MPI_Init/Comm_rank/Comm_size/Allreduce/Finalize,
cnnmpi.c:419-422,490,558) — with JAX SPMD over a named device mesh. The
per-sample, per-layer blocking MPI_Allreduce of the reference (3.6M
collectives per epoch at 8 ranks, SURVEY.md §3.2) becomes a single fused
gradient pmean inside one jitted step, lowered by XLA to ICI all-reduce.
"""

from .mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, make_mesh, local_device_count
from .dp import dp_shard_batch, make_dp_train_step, replicate
from .distributed import initialize_distributed, process_info
from .pp import make_pipeline_plan, make_pp_state, make_pp_train_step

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "PIPE_AXIS",
    "make_pipeline_plan",
    "make_pp_state",
    "make_pp_train_step",
    "make_mesh",
    "local_device_count",
    "dp_shard_batch",
    "make_dp_train_step",
    "replicate",
    "initialize_distributed",
    "process_info",
]
