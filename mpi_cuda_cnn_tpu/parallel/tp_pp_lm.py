"""Tensor parallelism x pipeline parallelism for the transformer LM —
Megatron sharding INSIDE the GPipe stages, over a ('pipe', 'model'
[, 'data']) mesh.

This is the classic 3D large-model layout (TP inside a node where the
interconnect is fastest, PP across, DP outside): the LM pipeline
(parallel/pp_lm.py) shards stacked blocks over 'pipe' and microbatches
over 'data'; this module additionally slices each block's heads and MLP
hidden over 'model', so one stage's block scan runs the SHARED Megatron
block (parallel/tp_sp.py tp_block_apply — the same f/g custom-VJP pair
and column/row regions as the TP x SP step, with full-sequence
attention instead of the ring):

- packed params: {'blocks': stacked (L, ...) head-structured leaves
  (to_tp_layout then stack_blocks), 'rest': replicated}. Block leaves
  shard 'pipe' on the leading (block) dim and 'model' on their head/
  hidden dim — wqkv (L, d, 3, H, hd) puts 'model' on H;
- activations are replicated over 'model' between regions (the f/g
  contract), so the GPipe ppermute over 'pipe' and the stage-0 embed /
  last-stage drain are untouched from pp_lm.py: every model rank runs
  them identically, and replicated-leaf gradients arrive exact on every
  rank (tp_sp.py's analysis), needing only pp_lm's psum over 'pipe';
- sliced-leaf gradients are exact per slice — never reduced over
  'model' (that would average unrelated slices); 'data' still pmeans
  everything.

The reference has none of these axes (SURVEY.md §2 checklist "PP:
absent", §5.7); composing them is where TPU pods actually train GPT-
scale models. Restrictions inherited and checked loudly: dense MLP only
(MoE -> EP meshes), depth % n_pipe == 0, heads/kv_heads/4d % n_model
== 0.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerLM
from .mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS
from .pp import _batch_spec
from .pp_lm import (
    _check_pp_lm,
    make_gpipe_local_loss,
    stack_blocks,
    unstack_blocks,
)
from ..utils.donation import donate_jit
from .tp_sp import (
    MOE_SPEC_TAILS,
    TP_SPEC_TAILS,
    _check_tp_sp,
    _make_tp_pair,
    from_tp_layout,
    to_tp_layout,
    tp_block_apply,
)

TrainState = dict[str, Any]

# 'model' placement per head-structured block leaf, AFTER stacking (the
# leading dim is the block dim, sharded over 'pipe') — tp_sp's single
# sliced-leaf table, not a copy: both the sharding specs below and the
# grad-clip norm classification key off it, so the two meshes cannot
# drift.
_TP_TAIL = TP_SPEC_TAILS
_MOE_TAIL = MOE_SPEC_TAILS


def _state_specs(state):
    """Specs by PATH over the whole packed state (params + mirrored
    optimizer buffers): a leaf under 'blocks' shards its leading dim
    over 'pipe' and, when its final key names a sliced weight AND its
    rank matches that weight's stacked rank, its head/hidden dim over
    'model'; everything else replicates. The rank guard keeps a
    same-named scalar wrapper buffer from inheriting a sliced spec."""

    def spec(path, leaf):
        keys = [str(getattr(p, "key", getattr(p, "name", ""))) for p in path]
        ndim = getattr(leaf, "ndim", 0)
        if "blocks" in keys and ndim >= 1:
            # MoE leaves live under blk["moe"] and reuse the w1/w2 names
            # with different ranks — the nested-key check keeps the two
            # tables from cross-matching.
            table = _MOE_TAIL if "moe" in keys else _TP_TAIL
            tail = table.get(keys[-1])
            if tail is not None and ndim == len(tail) + 1:
                return P(PIPE_AXIS, *tail)
            return P(PIPE_AXIS, *([None] * (ndim - 1)))
        return P()

    leaves, treedef = jax.tree_util.tree_flatten_with_path(state)
    return jax.tree_util.tree_unflatten(
        treedef, [spec(path, leaf) for path, leaf in leaves]
    )


def _check_tp_pp(model: TransformerLM, n_pipe: int, n_tp: int) -> None:
    _check_pp_lm(model, n_pipe)
    _check_tp_sp(model, n_tp)


def make_tp_pp_lm_state(model: TransformerLM, params, optimizer, mesh
                        ) -> TrainState:
    """Standard params -> head-structured TP layout -> stacked blocks,
    placed pipe x model sharded; optimizer buffers inherit leaf-for-leaf
    (path-matched, like pp_lm)."""
    _check_tp_pp(model, mesh.shape[PIPE_AXIS], mesh.shape[MODEL_AXIS])
    packed = stack_blocks(to_tp_layout(params, model))
    state = {
        "params": packed,
        "opt_state": optimizer.init(packed),
        "step": jnp.zeros((), jnp.int32),
    }
    specs = _state_specs(state)
    return jax.device_put(
        state,
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
    )


def unstack_tp_blocks(packed: dict, model: TransformerLM) -> dict:
    """Packed pipe x model layout -> the standard params tree (for eval,
    decode, checkpoint-portability, and parity tests)."""
    return from_tp_layout(unstack_blocks(packed, model.depth), model)


def make_tp_pp_lm_train_step(
    model: TransformerLM,
    optimizer: optax.GradientTransformation,
    mesh,
    state: TrainState,
    *,
    num_microbatches: int | None = None,
    compute_dtype=None,
    remat: bool = False,
    donate: bool = True,
    grad_clip: float = 0.0,
    attn_impl: str = "oracle",
    ce_chunk: int = 0,
    moe_aux_weight: float = 0.01,
):
    """Jitted GPipe x Megatron train step — with a 'seq' mesh axis, the
    FULL 4D layout (pipe x model x seq x data).

    step(state, toks_mb, tgt_mb) -> (state, {"loss": ...}); toks/tgt are
    (M, mb, S) int32 placed via pp_lm_shard_batch ('model' never shards
    data; with a 'seq' axis use pp_lm.sp_pp_shard_batch — positions
    shard over it). Each tick scans the shared Megatron block over the
    stage's local block slice; attention on the local heads is
    full-sequence ("flash"/"oracle" routed exactly as in the plain
    pipelined step) or, when the mesh has a 'seq' axis, the ring /
    ring-flash fold over it on the sequence shard — tp_sp.py's exact
    configuration (ring on H/n_tp local heads) riding the GPipe
    schedule's seq offset. ce_chunk fuses the drain CE either way;
    loss/grads additionally pmean over ('seq'[, 'data']).
    """
    from .sp import SEQ_AXIS, ring_attention, ring_flash_attention

    n_pipe = mesh.shape[PIPE_AXIS]
    n_tp = mesh.shape[MODEL_AXIS]
    n_seq = mesh.shape.get(SEQ_AXIS, 1)
    _check_tp_pp(model, n_pipe, n_tp)
    has_data = DATA_AXIS in mesh.axis_names
    M = num_microbatches or n_pipe
    cd = compute_dtype

    if n_seq > 1:
        if attn_impl == "ring":
            attn_body = ring_attention
        elif attn_impl == "ring_flash":
            attn_body = ring_flash_attention
        else:
            raise ValueError(
                f"attn_impl {attn_impl!r} with a 'seq' axis must be "
                "'ring' or 'ring_flash' (positions are sharded; each "
                "stage's attention is the sequence fold on the local "
                "heads)"
            )

        def attn(q, k, v):
            if attn_impl == "ring_flash" and q.shape[1] % 128:
                raise ValueError(
                    f"attn_impl='ring_flash' needs the per-shard "
                    f"sequence to be a multiple of 128: global "
                    f"S={q.shape[1] * n_seq} over seq={n_seq} devices "
                    f"gives s_local={q.shape[1]}"
                )
            return attn_body(q, k, v, axis=SEQ_AXIS, causal=True)
    else:
        from ..train.lm import get_attn_fn

        attn = get_attn_fn(attn_impl)
    tp_copy, tp_reduce = _make_tp_pair(MODEL_AXIS)
    w = (lambda t: t.astype(cd)) if cd else (lambda t: t)

    def stage_body(blocks, x, pos):
        def body(carry, blk):
            x, aux = carry
            x, a = tp_block_apply(
                blk, x, attn=attn,
                rope_pos=pos if model.pos == "rope" else None,
                w=w, tp_copy=tp_copy, tp_reduce=tp_reduce,
                moe_top_k=model.moe_top_k,
            )
            return (x, aux + a), None

        (x, aux), _ = lax.scan(body, (x, jnp.float32(0)), blocks)
        return x, aux

    # The whole GPipe schedule (embed / tick / ppermute / drain) is
    # pp_lm's, verbatim — the model ranks run it identically on
    # replicated activations; only the stage body is Megatron-sliced.
    # With a 'seq' axis the schedule's buffers hold the local sequence
    # shard and positions carry its absolute offset.
    # MoE aux at weight/n_tp in the differentiated loss: every upstream
    # value reaches it through tp_copy (psum backward) and the aux is
    # replicated across 'model' — 1/n_tp makes the psum restore exactly
    # one contribution; the metric gets the missing share back below.
    local_loss = make_gpipe_local_loss(
        model, M=M, n_pipe=n_pipe, compute_dtype=cd, remat=remat,
        ce_chunk=ce_chunk, stage_body=stage_body,
        seq_axis=SEQ_AXIS if n_seq > 1 else None, n_seq=n_seq,
        moe_aux_weight=moe_aux_weight / n_tp,
    )
    specs = _state_specs(state)  # shard_map specs AND the clip's
    #                              sliced-leaf classification below

    def step(state, toks_mb, tgt_mb):
        (loss, aux), grads = jax.value_and_grad(
            local_loss, has_aux=True
        )(state["params"], toks_mb, tgt_mb)
        loss = loss + moe_aux_weight * (1.0 - 1.0 / n_tp) * aux
        # Block grads: stage-local over 'pipe'; over 'model', sliced
        # leaves are exact per slice and replicated leaves (ln) are
        # identical on every rank (tp_sp.py's gradient analysis) — no
        # 'model' reduction. The rest tree got only its OWN stage's
        # contribution: psum over 'pipe' restores it, identically on
        # every model rank.
        grads = {
            "blocks": grads["blocks"],
            "rest": jax.tree.map(
                lambda g: lax.psum(g, PIPE_AXIS), grads["rest"]
            ),
        }
        loss = lax.psum(loss, PIPE_AXIS)
        # seq (and data) shards hold different tokens of the same
        # logical batch -> pmean everything over them, exactly as in
        # the plain SP step; never over 'model'.
        reduce_axes = tuple(
            a for a, on in ((SEQ_AXIS, n_seq > 1), (DATA_AXIS, has_data))
            if on
        )
        if reduce_axes:
            grads = jax.tree.map(
                lambda g: lax.pmean(g, reduce_axes), grads
            )
            loss = lax.pmean(loss, reduce_axes)
        if grad_clip > 0:
            # Each logical parameter once: sliced block leaves are
            # disjoint over BOTH 'pipe' and 'model'; ln block leaves are
            # disjoint over 'pipe' only (identical across 'model'); the
            # repaired rest is identical everywhere (post-pmean, all of
            # it replicated across seq/data). The sliced-vs-replicated
            # classification is the shared helper's, keyed off the SAME
            # specs the state is sharded with.
            from ..train.optimizer import (
                clip_grads_by_global_sq,
                grad_sq,
                split_grad_sq,
            )

            sliced, rep = split_grad_sq(
                grads["blocks"], specs["params"]["blocks"], MODEL_AXIS
            )
            g2 = lax.psum(sliced, MODEL_AXIS) + rep
            gn2 = lax.psum(g2, PIPE_AXIS) + grad_sq(grads["rest"])
            grads = clip_grads_by_global_sq(grads, gn2, grad_clip)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return (
            {"params": params, "opt_state": opt_state,
             "step": state["step"] + 1},
            {"loss": loss},
        )

    if n_seq > 1:
        from .pp_lm import sp_pp_batch_spec

        bspec = sp_pp_batch_spec(mesh)
    else:
        bspec = _batch_spec(mesh)
    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(specs, bspec, bspec),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return donate_jit(sharded, donate=donate)
