"""Tensor parallelism over the 'model' mesh axis.

The reference has NO tensor parallelism — every rank holds all 360,810
params (full buffers per rank, cnnmpi.c:93-103; SURVEY.md §2 parallelism
checklist: "TP: absent"). This module fills the seam SURVEY.md §7 stage 5
left open ("a 'model' axis seam") the idiomatic TPU way: GSPMD. Instead of
hand-writing sharded matmuls + collectives (the Megatron/NCCL pattern a GPU
port would translate), we

- assign each parameter a PartitionSpec over the ('data', 'model') mesh:
  output-feature sharding for Conv kernels (kh,kw,cin,cout -> shard cout)
  and Dense kernels (d_in,features -> shard features), biases to match,
  small heads (features not divisible by the axis) replicated;
- place the train state with those shardings once at init;
- jit the *plain* train step: XLA's sharding propagation derives every
  collective — all-gathers where a sharded layer output feeds the next
  layer, the gradient all-reduce over 'data' from the batch-mean loss, and
  reduce-scatters for the sharded gradients. Collectives ride ICI by mesh
  construction.

This composes with DP transparently: a Mesh("data": N, "model": M) runs
N-way data parallelism and M-way tensor parallelism from the same step
function with zero code difference (the pure-DP path in dp.py keeps the
explicit shard_map/psum formulation as the readable SPMD reference).
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, MODEL_AXIS
from ..utils.donation import donate_jit

TrainState = dict[str, Any]


def tp_param_specs(model, mesh, axis: str = MODEL_AXIS) -> list[dict]:
    """PartitionSpec pytree (same structure as model.init's params) sharding
    each layer's output features over `axis`.

    A layer whose feature count does not divide the axis size is replicated
    (the classifier head: 10 classes over an 8-way axis); parameterless
    layers (pools, flatten) get empty specs.
    """
    n = mesh.shape.get(axis, 1)
    specs: list[dict] = []
    for layer in model.layers:
        features = getattr(layer, "features", None)
        if features is None:
            specs.append({})
        elif n > 1 and features % n == 0:
            ndim_w = 4 if hasattr(layer, "kernel") else 2  # Conv HWIO / Dense
            specs.append({"w": P(*([None] * (ndim_w - 1)), axis), "b": P(axis)})
        else:
            specs.append({"w": P(), "b": P()})
    return specs


def shard_params(params, model, mesh, axis: str = MODEL_AXIS):
    """Place params on the mesh per tp_param_specs. The replicated-init +
    shard step replaces the reference's per-rank full copies."""
    specs = tp_param_specs(model, mesh, axis)
    return jax.device_put(
        params,
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
    )


def make_tp_state(model, params, optimizer, mesh, axis: str = MODEL_AXIS) -> TrainState:
    """Build the train state with TP-sharded params. The optimizer state is
    created FROM the sharded params, so its zeros_like buffers (momentum
    etc.) inherit the same shardings leaf-for-leaf."""
    params = shard_params(params, model, mesh, axis)
    return {
        "params": params,
        "opt_state": optimizer.init(params),
        "step": jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
        ),
    }


def _step_body(
    loss_fn: Callable,
    optimizer,
    augment=None,
    aug_seed: int = 0,
    grad_accum: int = 1,
):
    """The one train-step body both TP entry points jit (the GSPMD twin of
    dp._make_step_body — but with NO explicit collective: the batch-mean
    loss over the 'data'-sharded batch lowers to partial sums + an ICI
    all-reduce, the intent of the reference's MPI_Allreduce,
    cnnmpi.c:490).

    `augment` is keyed by step only (this is a GLOBAL program — per-sample
    keys fold in batch position inside make_augment, so shards still draw
    independent transforms)."""
    from .dp import _local_grads

    def step(state: TrainState, x, y):
        if augment is not None:
            x = augment(
                jax.random.fold_in(jax.random.key(aug_seed), state["step"]), x
            )
        loss, aux, grads = _local_grads(
            loss_fn, state["params"], x, y, grad_accum
        )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        return new_state, {"loss": loss, **aux}

    return step


def make_tp_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    donate: bool = True,
    augment=None,
    aug_seed: int = 0,
    grad_accum: int = 1,
):
    """The GSPMD train step: a plain jitted step over sharded inputs.

    Params sharded on 'model' make XLA partition the matmuls and insert
    the activation all-gathers. Shardings flow from the input arrays —
    callers place state via make_tp_state and batches via shard_batch_2d.
    """
    step = _step_body(loss_fn, optimizer, augment, aug_seed, grad_accum)
    return donate_jit(step, donate=donate)


def make_tp_scan_epoch(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    num_classes: int,
    *,
    donate: bool = True,
    augment=None,
    aug_seed: int = 0,
    grad_accum: int = 1,
):
    """Scanned-epoch twin of dp.make_dp_scan_epoch for the GSPMD path:
    lax.scan over a batch-index permutation with the uint8 dataset
    device-resident; normalization/one-hot on device (cnn.c:457,462-464)."""
    from ..data.pipeline import PIXEL_SCALE

    step = _step_body(loss_fn, optimizer, augment, aug_seed, grad_accum)

    def epoch(state: TrainState, images, labels, perm):
        def body(state, idx):
            x = images[idx].astype(jnp.float32) / jnp.float32(PIXEL_SCALE)
            y = jax.nn.one_hot(labels[idx], num_classes, dtype=jnp.float32)
            return step(state, x, y)

        state, metrics = jax.lax.scan(body, state, perm)
        return state, jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)

    return donate_jit(epoch, donate=donate)


def lm_tp_specs(model, mesh, axis: str = MODEL_AXIS) -> dict:
    """PartitionSpec pytree for a TransformerLM's params — Megatron-style
    placement expressed as GSPMD shardings (the same design as
    tp_param_specs for the CNN family; models/transformer.py init):

    - attention/MLP input projections (wqkv | wq/wkv, w1): OUTPUT features
      over `axis` (column parallel);
    - attention output / MLP down projections (wo, w2): INPUT dim over
      `axis` (row parallel — their activation input is already sharded
      from the previous matmul, so XLA's partitioner keeps the pair
      collective-free until the residual add's reduce);
    - token embedding + head: vocab dim over `axis` (the classic
      vocab-parallel embedding; the loss's full-vocab softmax makes XLA
      insert the logit gather/reduce);
    - layernorms, positional table, MoE gate: replicated;
    - MoE experts: hidden dim over `axis` (w1 (E,d,4d) column, w2 (E,4d,d)
      row) — TP inside every expert.

    Any dim not divisible by the axis size falls back to replicated for
    that leaf — the step stays correct (GSPMD), just less sharded.
    """
    n = mesh.shape.get(axis, 1)

    def shard(dim_index):
        """P sharding dimension `dim_index` of a leaf, if divisible."""
        def spec(leaf):
            if n <= 1 or leaf.ndim == 0:
                return P()
            i = dim_index % leaf.ndim
            if leaf.shape[i] % n:
                return P()
            e = [None] * leaf.ndim
            e[i] = axis
            return P(*e)
        return spec

    # Shapes only — eval_shape traces init without materializing a second
    # full parameter set (callers already hold the real params).
    params = jax.eval_shape(lambda: model.init(jax.random.key(0)))
    col, row = shard(-1), shard(-2)
    vocab0 = shard(0)

    def block_specs(blk):
        s = {
            "ln1": jax.tree.map(lambda _: P(), blk["ln1"]),
            "ln2": jax.tree.map(lambda _: P(), blk["ln2"]),
            "wo": row(blk["wo"]),
        }
        if "wqkv" in blk:
            s["wqkv"] = col(blk["wqkv"])
        else:
            s["wq"] = col(blk["wq"])
            s["wkv"] = col(blk["wkv"])
        if "moe" in blk:
            s["moe"] = {
                "gate": P(),
                "w1": col(blk["moe"]["w1"]),
                "w2": row(blk["moe"]["w2"]),
            }
        else:
            s["w1"] = col(blk["w1"])
            s["w2"] = row(blk["w2"])
        return s

    specs = {
        "tok_emb": vocab0(params["tok_emb"]),
        "ln_f": jax.tree.map(lambda _: P(), params["ln_f"]),
        "head": col(params["head"]),
        "blocks": [block_specs(b) for b in params["blocks"]],
    }
    if "pos_emb" in params:
        specs["pos_emb"] = P()
    return specs


def make_lm_tp_state(model, params, optimizer, mesh,
                     axis: str = MODEL_AXIS) -> TrainState:
    """LM train state with TP-sharded params (lm_tp_specs); the optimizer
    state inherits the shardings leaf-for-leaf. Use with the PLAIN jitted
    LM step (train/lm.make_lm_train_step) — GSPMD derives the collectives
    from the placement, exactly like the CNN make_tp_state path."""
    params = shard_lm_params(model, params, mesh, axis)
    return {
        "params": params,
        "opt_state": optimizer.init(params),
        "step": jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
        ),
    }


def shard_lm_params(model, params, mesh, axis: str = MODEL_AXIS):
    """Place a STANDARD-layout params tree with the Megatron TP
    shardings (lm_tp_specs) — the sharded-INFERENCE entry point.

    generate()'s prefill + KV-cached decode scan (models/generate.py) is
    a plain jitted program, so GSPMD partitions the whole serving path
    from this placement alone: column/row-parallel projections per
    decode step, the KV cache head-sharded over `axis` because it is
    built from the sharded k/v projections — no decode-code changes.
    Decode-parity tested against single-device generate
    (tests/test_tp.py)."""
    specs = lm_tp_specs(model, mesh, axis)
    return jax.device_put(
        params,
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
    )


def shard_batch_2d(batch, mesh, axis: str = DATA_AXIS):
    """Shard a host batch's leading dim over 'data' (replicated over
    'model'): every model-group works on the same samples."""
    return jax.device_put(batch, NamedSharding(mesh, P(axis)))


def make_tp_eval_step(predict_fn: Callable):
    """GSPMD eval: jit the plain forward; shardings flow from the arrays."""
    return jax.jit(predict_fn)
