"""Pipeline parallelism over a 'pipe' mesh axis.

The reference has NO pipeline parallelism — its layers execute sequentially
in one process (cnn.c:255-267; SURVEY.md §2 parallelism checklist: "PP:
absent — no stage assignment, no micro-batching"). This module fills that
seam the SPMD way, as a capability beyond reference parity:

- the Sequential's layers are split into S contiguous *stages*, balanced by
  a FLOPs estimate (`make_pipeline_plan`);
- each stage's params are flattened and packed into one row of an
  (S, P_max) array whose leading dim is sharded over the 'pipe' mesh axis —
  every device holds ONLY its stage's weights (1/S of the model, the memory
  property that defines PP);
- one jitted shard_map runs the GPipe schedule: a `lax.scan` over
  M + S - 1 ticks in which every device applies its own stage
  (`lax.switch` on `axis_index('pipe')`), then hands its activations to the
  next stage with `lax.ppermute` — a neighbor transfer that rides ICI by
  mesh construction;
- the loss is computed on the last stage as each microbatch drains, masked
  to zero elsewhere; `jax.grad` differentiates the whole schedule, and the
  transpose of the forward ppermute chain IS the backward pipeline (reverse
  shifts carrying cotangents), so fwd and bwd share one code path.

Composes with DP on a ('pipe', 'data') mesh: the microbatch dim shards over
'data', gradients pmean over 'data' exactly as in dp.py. Stage buffers are
padded to the widest stage (A_max activations, P_max params); padding costs
memory, not FLOPs — the switch branches only compute their real shapes.

TP x PP composes on a ('pipe', 'model'[, 'data']) mesh (n_model > 1 in the
plan): inside each stage, Conv/Dense output features are sliced over
'model' Megatron-style — the packed params become (S, M, Pm_max), each
device holds its stage's model-shard, each sliced layer computes its
feature slice and `lax.all_gather`s the activation back to full before
the next layer (the gather's transpose routes the cotangent slices back —
reduce-scatter — so backward needs no extra code). Layers that do not
expose a divisible feature count (pools, heads, Residual blocks) stay
replicated across 'model': every rank computes them identically, which is
correct (same input, same weights) and costs only the unsliced FLOPs.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..obs.trace import annotate
from ..ops.activations import stable_softmax
from ..ops.losses import softmax_cross_entropy, squared_error_total
from .mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS
from ..utils.donation import donate_jit

TrainState = dict[str, Any]


def _zeros_init(key, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def _layer_cost(layer, in_shape, out_shape, params) -> int:
    """Forward-MAC estimate used to balance stages. Conv: every output
    position reuses the whole kernel; Dense: one MAC per weight; param-free
    layers cost their element count (VPU traffic, negligible next to MXU
    work but keeps ties deterministic)."""
    wsize = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    if not wsize:
        return int(np.prod(in_shape))
    positions = int(np.prod(out_shape[:-1])) if len(out_shape) > 1 else 1
    return wsize * positions


def _partition_balanced(costs: list[int], n_stages: int) -> list[tuple[int, ...]]:
    """Contiguous partition of layer indices into n_stages groups minimizing
    the max group cost (classic linear-partition DP; n is tiny)."""
    n = len(costs)
    if n_stages > n:
        raise ValueError(f"{n_stages} stages > {n} layers")
    prefix = np.concatenate([[0], np.cumsum(costs)])

    def seg(i, j):  # cost of layers [i, j)
        return prefix[j] - prefix[i]

    # best[k][j] = minimal max-cost splitting the first j layers into k groups
    best = np.full((n_stages + 1, n + 1), np.inf)
    cut = np.zeros((n_stages + 1, n + 1), np.int64)
    best[0][0] = 0
    for k in range(1, n_stages + 1):
        for j in range(k, n + 1):
            for i in range(k - 1, j):
                c = max(best[k - 1][i], seg(i, j))
                if c < best[k][j]:
                    best[k][j] = c
                    cut[k][j] = i
    bounds = [n]
    for k in range(n_stages, 0, -1):
        bounds.append(int(cut[k][bounds[-1]]))
    bounds.reverse()
    return [tuple(range(bounds[k], bounds[k + 1])) for k in range(n_stages)]


@dataclasses.dataclass(frozen=True)
class PipelinePlan:
    """Static description of a pipelined model: which layers run on which
    stage, the padded buffer widths, and the flatten/unflatten metadata."""

    model: Any
    n_stages: int
    stage_layers: tuple[tuple[int, ...], ...]
    stage_in_shapes: tuple[tuple[int, ...], ...]  # per-sample input shape per stage
    layer_in_shapes: tuple[tuple[int, ...], ...]  # per-sample input shape per layer
    param_shapes: tuple[tuple[tuple[int, ...], ...], ...]  # per stage: leaf shapes
    param_treedefs: tuple
    num_classes: int
    a_max: int  # flat per-sample activation width crossing any stage boundary
    p_max: int  # padded per-stage flat param length (PER MODEL SHARD when
    #   n_model > 1)
    backend: str = "xla"
    compute_dtype: Any = None  # per-stage compute cast (e.g. bf16); master
    #   params and the ppermute activation/param buffers stay f32
    n_model: int = 1  # TP degree inside each stage ('model' mesh axis)
    layer_sliced: tuple[bool, ...] = ()  # per LAYER: features sliced over
    #   'model'? (leaves whose last dim == features are sliced; the
    #   activation is gathered back to full after the layer)
    remat: bool = False  # jax.checkpoint around each stage fn: backward
    #   re-runs the stage instead of saving its internal activations —
    #   the scan carry (one A_max boundary buffer per tick) becomes the
    #   only live activation state, exactly the memory regime long
    #   pipelined models need
    fsdp: bool = False  # ZeRO over 'data' INSIDE each stage row: the
    #   packed (S[, M], P_max) params shard their last dim over 'data';
    #   the step all-gathers the row, computes, then reduce-scatters the
    #   mean gradient back to shards (see _make_step_body)


def _slice_last(leaf, m: int, n: int):
    """m-th of n equal slices of the last dim."""
    w = leaf.shape[-1] // n
    return leaf[..., m * w : (m + 1) * w]


def _local_leaf_shape(shape, layer_features, sliced: bool, n_model: int):
    """Shape of one model-shard's copy of a stage leaf: last dim / n_model
    for leaves carrying the layer's feature dim, unchanged otherwise."""
    if sliced and shape and shape[-1] == layer_features:
        return shape[:-1] + (shape[-1] // n_model,)
    return tuple(shape)


def make_pipeline_plan(
    model, n_stages: int, *, backend: str = "xla", compute_dtype=None,
    n_model: int = 1, remat: bool = False, fsdp_degree: int = 1,
) -> PipelinePlan:
    """Split `model` (a Sequential) into n_stages balanced stages;
    n_model > 1 additionally slices each stage's Conv/Dense features
    over the 'model' mesh axis (TP x PP). fsdp_degree > 1 pads P_max to a
    multiple of the 'data'-axis size and marks the plan for ZeRO row
    sharding (FSDP x PP)."""
    key = jax.random.key(0)
    shape = model.input_shape
    layer_in_shapes, costs, zero_params, layer_sliced = [], [], [], []
    for layer in model.layers:
        p, out = layer.init(key, shape, _zeros_init)
        layer_in_shapes.append(tuple(shape))
        costs.append(_layer_cost(layer, shape, out, p))
        zero_params.append(p)
        f = getattr(layer, "features", None)
        layer_sliced.append(
            bool(n_model > 1 and f is not None and f % n_model == 0)
        )
        shape = out
    num_classes = int(shape[-1])
    stage_layers = _partition_balanced(costs, n_stages)

    stage_in_shapes, param_shapes, param_treedefs, p_sizes = [], [], [], []
    boundary_widths = [int(np.prod(model.input_shape))]
    for idxs in stage_layers:
        stage_in_shapes.append(layer_in_shapes[idxs[0]])
        stage_p = [zero_params[i] for i in idxs]
        leaves, treedef = jax.tree.flatten(stage_p)
        # Local (per-model-shard) leaf shapes: flatten order must match
        # tree order, so walk per layer and re-flatten.
        local_shapes = []
        for i in idxs:
            f = getattr(model.layers[i], "features", None)
            for leaf in jax.tree.leaves(zero_params[i]):
                local_shapes.append(_local_leaf_shape(
                    leaf.shape, f, layer_sliced[i], n_model
                ))
        param_shapes.append(tuple(local_shapes))
        param_treedefs.append(treedef)
        p_sizes.append(sum(int(np.prod(s)) for s in local_shapes))
        end = idxs[-1] + 1
        out_shape = layer_in_shapes[end] if end < len(model.layers) else shape
        boundary_widths.append(int(np.prod(out_shape)))
    p_max = max(p_sizes) if p_sizes else 1
    if fsdp_degree > 1:
        # The ZeRO row shard splits P_max evenly over 'data'.
        p_max += -p_max % fsdp_degree
    return PipelinePlan(
        model=model,
        n_stages=n_stages,
        stage_layers=tuple(stage_layers),
        stage_in_shapes=tuple(stage_in_shapes),
        layer_in_shapes=tuple(layer_in_shapes),
        param_shapes=tuple(param_shapes),
        param_treedefs=tuple(param_treedefs),
        num_classes=num_classes,
        a_max=max(boundary_widths),
        p_max=p_max,
        backend=backend,
        compute_dtype=compute_dtype,
        n_model=n_model,
        layer_sliced=tuple(layer_sliced),
        remat=remat,
        fsdp=fsdp_degree > 1,
    )


def _stage_local_leaves(plan: PipelinePlan, params, idxs, m: int):
    """Stage leaves for model-shard m, in tree order, feature dims sliced."""
    leaves = []
    for i in idxs:
        f = getattr(plan.model.layers[i], "features", None)
        for leaf in jax.tree.leaves(params[i]):
            if plan.layer_sliced[i] and leaf.shape and leaf.shape[-1] == f:
                leaf = _slice_last(leaf, m, plan.n_model)
            leaves.append(leaf)
    return leaves


def pack_params(plan: PipelinePlan, params) -> jnp.ndarray:
    """Model params (the Sequential's per-layer list) -> (S, P_max) f32
    array — or (S, M, P_max) under TP x PP — row [s(, m)] is stage s's
    (model-shard m's) leaves raveled and zero-padded."""

    def row(leaves):
        flat = (
            jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])
            if leaves
            else jnp.zeros((0,), jnp.float32)
        )
        return jnp.pad(flat, (0, plan.p_max - flat.shape[0]))

    if plan.n_model == 1:
        return jnp.stack([
            row(jax.tree.leaves([params[i] for i in idxs]))
            for idxs in plan.stage_layers
        ])
    return jnp.stack([
        jnp.stack([
            row(_stage_local_leaves(plan, params, idxs, m))
            for m in range(plan.n_model)
        ])
        for idxs in plan.stage_layers
    ])


def unpack_params(plan: PipelinePlan, packed) -> list:
    """(S, P_max) / (S, M, P_max) -> the Sequential's per-layer params list
    (for eval, checkpointing, and parity tests against the unpipelined
    model). Under TP x PP, sliced leaves are re-concatenated from the
    model shards; replicated leaves read shard 0."""
    packed = jnp.asarray(packed)
    out: list = [None] * len(plan.model.layers)
    for s, idxs in enumerate(plan.stage_layers):
        if plan.n_model == 1:
            stage = _unpack_stage(plan, s, packed[s])
        else:
            shards = [
                _unpack_stage(plan, s, packed[s, m])
                for m in range(plan.n_model)
            ]
            stage = []
            for li, i in enumerate(idxs):
                f = getattr(plan.model.layers[i], "features", None)
                merged = jax.tree.map(
                    # Loop vars bound as defaults (ruff B023): the map
                    # runs immediately, but the binding makes it obvious.
                    lambda *ls, sliced=plan.layer_sliced[i], f=f: (
                        jnp.concatenate(ls, axis=-1)
                        if sliced
                        and ls[0].shape and ls[0].shape[-1] * plan.n_model == f
                        else ls[0]
                    ),
                    *[sh[li] for sh in shards],
                )
                stage.append(merged)
        for i, p in zip(idxs, stage):
            out[i] = p
    return out


def _unpack_stage(plan: PipelinePlan, s: int, flat: jnp.ndarray) -> list:
    leaves, off = [], 0
    for shp in plan.param_shapes[s]:
        size = int(np.prod(shp))
        leaves.append(flat[off:off + size].reshape(shp))
        off += size
    return jax.tree.unflatten(plan.param_treedefs[s], leaves)


def _stage_fns(plan: PipelinePlan, mb: int) -> list[Callable]:
    """One (flat_params, flat_x) -> flat_y function per stage, all with the
    identical (mb, A_max) signature `lax.switch` requires; each branch only
    computes its stage's true shapes.

    Under TP x PP (plan.n_model > 1) flat_p is this device's model-shard:
    sliced layers compute their feature slice, then `all_gather` the
    activation back to full over 'model' (every device of a model group is
    at the same pipe stage, so the branch — and its collective — matches
    across the group). The gather's transpose is the reduce-scatter that
    routes each shard its cotangent slice in backward."""
    fns = []
    for s, idxs in enumerate(plan.stage_layers):
        in_shape = plan.stage_in_shapes[s]
        in_size = int(np.prod(in_shape))

        def fn(flat_p, flat_x, s=s, idxs=idxs, in_shape=in_shape, in_size=in_size):
            stage_params = _unpack_stage(plan, s, flat_p)
            x = flat_x[:, :in_size].reshape((mb,) + in_shape)
            if plan.compute_dtype is not None:
                x = x.astype(plan.compute_dtype)
                stage_params = jax.tree.map(
                    lambda p: p.astype(plan.compute_dtype), stage_params
                )
            for i, p in zip(idxs, stage_params):
                x = plan.model.layers[i].apply(p, x, backend=plan.backend)
                if plan.layer_sliced[i]:
                    # (..., features/M) -> (..., features). Elementwise
                    # activations act per-feature, so gathering AFTER the
                    # activation is exact.
                    x = jax.lax.all_gather(
                        x, MODEL_AXIS, axis=x.ndim - 1, tiled=True
                    )
            y = x.reshape(mb, -1).astype(jnp.float32)
            return jnp.pad(y, ((0, 0), (0, plan.a_max - y.shape[1])))

        # remat: the backward pass re-runs the stage from (flat_p, flat_x)
        # instead of saving its per-layer activations; with the scan carry
        # already bounded to one (mb, A_max) boundary buffer, live
        # activation memory becomes O(stage boundary), not O(stage depth).
        fns.append(jax.checkpoint(fn) if plan.remat else fn)
    return fns


def _tp_replicated_mask(plan: PipelinePlan) -> np.ndarray:
    """(S, P_max) mask for TP x PP gradient repair: 1.0 over flat
    positions holding REPLICATED leaves, 0.0 over SLICED leaves (padding
    is 1.0 — its grads are zero, so the psum below is harmless).

    Why: the local loss is scaled by 1/n_model (every model rank of the
    last stage computes the full loss, so the SPMD objective sums it
    n_model times). Under that seeding a SLICED leaf's gradient arrives
    exact — every downstream all_gather's transpose is a psum-scatter,
    which performs the cross-rank reduction — but a REPLICATED leaf's
    per-rank copy receives only the cotangent that flowed through ITS
    rank's chain: the full loss for leaves downstream of every sliced
    layer (each rank re-computes them identically, each scaled 1/n_model),
    but a PARTIAL, rank-varying term for leaves upstream of a sliced
    layer (the psum-scatter hands each rank only its slice's
    contribution). In both cases the true gradient of the single logical
    parameter is the SUM over the rank copies — one masked
    `psum(MODEL_AXIS)` repairs both, with no rescale."""
    mask = np.ones((plan.n_stages, plan.p_max), np.float32)
    for s, idxs in enumerate(plan.stage_layers):
        # plan.param_shapes[s] lists the LOCAL leaf shapes in tree order;
        # replay the per-layer flatten to know which layer owns each leaf.
        off = 0
        shape_iter = iter(plan.param_shapes[s])
        for i in idxs:
            zero_p, _ = plan.model.layers[i].init(
                jax.random.key(0), plan.layer_in_shapes[i], _zeros_init
            )
            for _ in jax.tree.leaves(zero_p):
                shp = next(shape_iter)
                size = int(np.prod(shp)) if shp else 1
                if plan.layer_sliced[i]:
                    mask[s, off:off + size] = 0.0
                off += size
    return mask


def _make_local_loss(plan: PipelinePlan):
    """The per-device GPipe schedule. Returns local (masked) loss — nonzero
    only on the last stage — so value_and_grad never differentiates through
    a collective; cross-stage gradient flow rides the ppermute transposes.

    Under TP x PP the returned loss/metrics are additionally scaled by
    1/n_model (every model rank of the last stage holds the full logits and
    computes the full loss): summing over BOTH the pipe and model axes then
    reconstitutes the true value once, and the gradient scaling is repaired
    by _tp_grad_factor in the step body."""
    S = plan.n_stages
    C = plan.num_classes
    nm = plan.n_model
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def local_loss(flat_params, x_mb, y_mb):
        # flat_params: (1, P_max) local row — (1, 1, P_max) under TP x PP;
        # x_mb: (M, mb, H, W, C) f32; y_mb: (M, mb, C) one-hot.
        fp = flat_params[0, 0] if plan.n_model > 1 else flat_params[0]
        M, mb = x_mb.shape[0], x_mb.shape[1]
        fns = _stage_fns(plan, mb)
        s_idx = jax.lax.axis_index(PIPE_AXIS)
        feed = x_mb.reshape(M, mb, -1)
        feed = jnp.pad(feed, ((0, 0), (0, 0), (0, plan.a_max - feed.shape[-1])))

        def tick(carry, t):
            buf, loss_sum, etot_sum, acc_sum = carry
            # Stage 0 ingests microbatch t (clipped past M: those bubbles
            # never reach the last stage inside the scan, so they carry no
            # loss and no gradient); later stages read the shifted buffer.
            inp = jnp.where(s_idx == 0, feed[jnp.minimum(t, M - 1)], buf)
            with annotate("pp.stage_body"):
                y = jax.lax.switch(s_idx, fns, fp, inp)
            out_t = t - (S - 1)
            w = jnp.where(
                (s_idx == S - 1) & (out_t >= 0) & (out_t < M), 1.0, 0.0
            )
            logits = y[:, :C]
            yt = y_mb[jnp.clip(out_t, 0, M - 1)]
            loss_sum = loss_sum + w * softmax_cross_entropy(logits, yt)
            probs = stable_softmax(logits)
            etot_sum = etot_sum + w * squared_error_total(probs, yt)
            acc_sum = acc_sum + w * jnp.mean(
                (jnp.argmax(logits, -1) == jnp.argmax(yt, -1)).astype(jnp.float32)
            )
            with annotate("pp.ppermute_activations"):
                y = jax.lax.ppermute(y, PIPE_AXIS, fwd_perm)
            return (y, loss_sum, etot_sum, acc_sum), None

        carry0 = (jnp.zeros((mb, plan.a_max), jnp.float32),
                  jnp.float32(0), jnp.float32(0), jnp.float32(0))
        (_, loss_sum, etot_sum, acc_sum), _ = jax.lax.scan(
            tick, carry0, jnp.arange(M + S - 1)
        )
        # Per-microbatch means averaged over microbatches == the full-batch
        # means the unpipelined loss_fn reports (equal microbatch sizes).
        # The extra / nm makes the model-axis copies sum to the true value
        # (and under-seeds gradients by 1/nm — repaired per-segment by
        # _tp_grad_factor in the step body).
        return loss_sum / (M * nm), (etot_sum / (M * nm), acc_sum / (M * nm))

    return local_loss


def _state_specs(state: TrainState, n_stages: int, n_model: int = 1,
                 fsdp: bool = False):
    """PartitionSpecs for a PP train state: (S, ...)-leading leaves shard
    over 'pipe' (and their second dim over 'model' under TP x PP; params +
    matching optimizer buffers), scalars replicate. fsdp additionally
    shards the flat param dim (last) over 'data' — ZeRO's param +
    optimizer-state partitioning, inherited by every optimizer buffer
    because they share the packed row shape."""

    def spec(a):
        if getattr(a, "ndim", 0) >= 1 and a.shape[0] == n_stages:
            mid = [None] * (a.ndim - 1)
            if n_model > 1 and a.ndim >= 2 and a.shape[1] == n_model:
                mid[0] = MODEL_AXIS
            if fsdp and a.ndim >= 2:
                mid[-1] = DATA_AXIS
            return P(PIPE_AXIS, *mid)
        return P()

    return jax.tree.map(spec, state)


def make_pp_state(plan: PipelinePlan, params, optimizer, mesh) -> TrainState:
    """Pack + place the train state: stage rows on their pipe coordinate
    (model shards on their model coordinate under TP x PP; the flat dim
    over 'data' under FSDP x PP), optimizer state created FROM the packed
    array so its buffers inherit the sharding leaf-for-leaf."""
    last = DATA_AXIS if plan.fsdp else None
    row_spec = (
        P(PIPE_AXIS, MODEL_AXIS, last) if plan.n_model > 1
        else P(PIPE_AXIS, last)
    )
    packed = jax.device_put(
        pack_params(plan, params), NamedSharding(mesh, row_spec)
    )
    return {
        "flat_params": packed,
        "opt_state": optimizer.init(packed),
        "step": jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P())),
    }


def _batch_spec(mesh):
    """Microbatched arrays (M, mb, ...): mb shards over 'data' when the mesh
    has that axis; the microbatch dim is the schedule, never sharded."""
    return P(None, DATA_AXIS) if DATA_AXIS in mesh.axis_names else P(None)


def pp_shard_batch(batch, mesh):
    """Place host (M, mb, ...) microbatch arrays on the mesh."""
    return jax.device_put(batch, NamedSharding(mesh, _batch_spec(mesh)))


def microbatch(x, y, num_microbatches: int):
    """Split a (B, ...) batch into (M, B//M, ...) microbatch arrays."""
    if x.shape[0] % num_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {num_microbatches} microbatches"
        )
    split = lambda a: a.reshape((num_microbatches, -1) + a.shape[1:])
    return split(x), split(y)


def _make_step_body(plan: PipelinePlan, optimizer, mesh,
                    augment=None, aug_seed: int = 0,
                    grad_clip: float = 0.0):
    """The per-device PP(+DP) train-step body shared by the one-batch step
    and the scanned epoch (the PP twin of dp._make_step_body).

    `augment` runs on-device on the (flattened) microbatched inputs,
    keyed by (step, data-axis index) exactly like dp._make_step_body —
    pipe (and model) ranks draw the SAME key, so the stage-0 feed every
    rank computes against is identical across the pipe.

    plan.fsdp (ZeRO x GPipe): the local flat_params hold 1/n_data of the
    stage row. The step all-gathers the full row over 'data', runs the
    schedule, differentiates w.r.t. the FULL row, then one
    psum_scatter / n_data both averages the gradient across the data
    shards (the DP pmean) and hands each device exactly its shard's
    slice (the ZeRO reduce-scatter) — master params + optimizer state
    stay sharded; only the transient gathered row is ever full-width.

    grad_clip > 0 clips IN-STEP with the cross-rank global norm (the
    packed rows are sharded, so optax's clip_by_global_norm would see a
    per-rank partial norm): stage rows are disjoint over 'pipe' (psum
    their squared norms); under TP the sliced segments are disjoint over
    'model' (psum) while the psum-repaired replicated segments are
    identical on every model rank (count once, via the same rep_mask the
    repair uses); under FSDP the post-scatter slices are disjoint over
    'data' (psum). The scale application lives in the ONE shared helper
    (train/optimizer.py clip_grads_by_global_sq) so the semantics cannot
    drift from the LM steps'.
    """
    local_loss = _make_local_loss(plan)
    tp = plan.n_model > 1
    rep_mask = jnp.asarray(_tp_replicated_mask(plan)) if tp else None
    metric_axes = (PIPE_AXIS, MODEL_AXIS) if tp else PIPE_AXIS
    has_data = DATA_AXIS in mesh.axis_names
    n_data = mesh.shape.get(DATA_AXIS, 1)
    if plan.fsdp and n_data <= 1:
        raise ValueError("FSDP x PP needs a 'data' mesh axis of size > 1")

    def step(state: TrainState, x_mb, y_mb):
        if augment is not None:
            key = jax.random.fold_in(jax.random.key(aug_seed), state["step"])
            if has_data:
                key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
            flat_x = x_mb.reshape((-1,) + x_mb.shape[2:])
            x_mb = augment(key, flat_x).reshape(x_mb.shape)
        local = state["flat_params"]
        full = (
            jax.lax.all_gather(local, DATA_AXIS, axis=local.ndim - 1,
                               tiled=True)
            if plan.fsdp else local
        )
        (loss, (etot, acc)), grads = jax.value_and_grad(
            local_loss, has_aux=True
        )(full, x_mb, y_mb)
        if tp:
            # Restore exact gradients for the replicated segments: sum the
            # rank copies over 'model' (see _tp_replicated_mask); sliced
            # segments pass through. (1, 1, P_max) local grads broadcast.
            row = rep_mask[jax.lax.axis_index(PIPE_AXIS)]
            grads = jax.tree.map(
                lambda g: g * (1.0 - row)
                + jax.lax.psum(g * row, MODEL_AXIS),
                grads,
            )
        # The masked loss lives on the last stage only: one psum replicates
        # it (and the metric sums) across the pipe (and, under TP x PP, the
        # 1/n_model-scaled model-axis copies).
        loss, etot, acc = (
            jax.lax.psum(m, metric_axes) for m in (loss, etot, acc)
        )
        if plan.fsdp:
            grads = jax.lax.psum_scatter(
                grads, DATA_AXIS, scatter_dimension=grads.ndim - 1,
                tiled=True,
            ) / n_data
        elif has_data:
            grads = jax.lax.pmean(grads, DATA_AXIS)
        if has_data:
            loss, etot, acc = (
                jax.lax.pmean(m, DATA_AXIS) for m in (loss, etot, acc)
            )
        if grad_clip > 0:
            from ..train.optimizer import clip_grads_by_global_sq

            sq = jnp.square(grads).astype(jnp.float32)
            if tp:
                row = rep_mask[jax.lax.axis_index(PIPE_AXIS)]
                if plan.fsdp:
                    # Post-scatter grads hold the 1/n_data slice of the
                    # row's last dim — align the full-width mask to it.
                    w = grads.shape[-1]
                    row = jax.lax.dynamic_slice_in_dim(
                        row, jax.lax.axis_index(DATA_AXIS) * w, w, -1
                    )
                g2 = jax.lax.psum(jnp.sum(sq * (1.0 - row)), MODEL_AXIS) \
                    + jnp.sum(sq * row)
            else:
                g2 = jnp.sum(sq)
            gn2 = jax.lax.psum(g2, PIPE_AXIS)
            if plan.fsdp:
                # Data shards are disjoint slices — the rep-segment
                # pieces too (each data rank holds different positions
                # of the repaired copy), so one psum completes BOTH
                # sums above.
                gn2 = jax.lax.psum(gn2, DATA_AXIS)
            grads = clip_grads_by_global_sq(grads, gn2, grad_clip)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["flat_params"]
        )
        flat = optax.apply_updates(state["flat_params"], updates)
        new_state = {"flat_params": flat, "opt_state": opt_state,
                     "step": state["step"] + 1}
        return new_state, {"loss": loss, "etotal": etot, "acc": acc}

    return step


def make_pp_train_step(
    plan: PipelinePlan,
    optimizer: optax.GradientTransformation,
    mesh,
    state: TrainState,
    *,
    donate: bool = True,
    augment=None,
    aug_seed: int = 0,
    grad_clip: float = 0.0,
):
    """Build the jitted PP(+DP) train step.

    step(state, x_mb, y_mb) -> (state, metrics); x_mb (M, mb, H, W, C) and
    y_mb (M, mb, C) placed via pp_shard_batch. Metrics match the DP/TP
    steps' {loss, etotal, acc} means, so the Trainer can treat all three
    parallel modes uniformly.
    """
    step = _make_step_body(plan, optimizer, mesh, augment, aug_seed,
                           grad_clip)
    specs = _state_specs(state, plan.n_stages, plan.n_model, plan.fsdp)
    bspec = _batch_spec(mesh)
    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(specs, bspec, bspec),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return donate_jit(sharded, donate=donate)


def make_pp_scan_epoch(
    plan: PipelinePlan,
    optimizer: optax.GradientTransformation,
    mesh,
    state: TrainState,
    num_classes: int,
    num_microbatches: int,
    *,
    donate: bool = True,
    augment=None,
    aug_seed: int = 0,
    grad_clip: float = 0.0,
):
    """Scanned-epoch twin of dp.make_dp_scan_epoch for the pipelined path:
    lax.scan over a batch-index permutation with the uint8 dataset
    device-resident; each scan step microbatches its batch and runs the
    GPipe schedule.

    epoch_fn(state, images_u8, labels_i32, perm) -> (state, metric_sums);
    perm (nsteps, local_batch) with the batch dim sharded over 'data'
    (dp.dp_shard_perm places it); local_batch must be a multiple of
    num_microbatches.
    """
    from ..data.pipeline import PIXEL_SCALE

    step = _make_step_body(plan, optimizer, mesh, augment, aug_seed,
                           grad_clip)
    M = num_microbatches

    def epoch(state: TrainState, images, labels, perm):
        def body(state, idx):
            x = images[idx].astype(jnp.float32) / jnp.float32(PIXEL_SCALE)
            y = jax.nn.one_hot(labels[idx], num_classes, dtype=jnp.float32)
            x_mb = x.reshape((M, -1) + x.shape[1:])
            y_mb = y.reshape((M, -1) + y.shape[1:])
            return step(state, x_mb, y_mb)

        state, metrics = jax.lax.scan(body, state, perm)
        return state, jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)

    specs = _state_specs(state, plan.n_stages, plan.n_model, plan.fsdp)
    sharded = jax.shard_map(
        epoch,
        mesh=mesh,
        in_specs=(specs, P(), P(), _batch_spec(mesh)),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return donate_jit(sharded, donate=donate)


def make_pp_forward(plan: PipelinePlan, mesh):
    """Jitted pipelined forward: (flat_params, x_mb) -> (M, mb, C) logits.
    Runs the same schedule loss-free, collecting each tick's output; the
    last stage's drained ticks are the logits, psum-broadcast to all pipe
    devices (sharded over 'data' if present)."""
    S = plan.n_stages
    C = plan.num_classes
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def forward(flat_params, x_mb):
        if plan.fsdp:
            flat_params = jax.lax.all_gather(
                flat_params, DATA_AXIS, axis=flat_params.ndim - 1, tiled=True
            )
        fp = flat_params[0, 0] if plan.n_model > 1 else flat_params[0]
        M, mb = x_mb.shape[0], x_mb.shape[1]
        fns = _stage_fns(plan, mb)
        s_idx = jax.lax.axis_index(PIPE_AXIS)
        feed = x_mb.reshape(M, mb, -1)
        feed = jnp.pad(feed, ((0, 0), (0, 0), (0, plan.a_max - feed.shape[-1])))

        def tick(buf, t):
            inp = jnp.where(s_idx == 0, feed[jnp.minimum(t, M - 1)], buf)
            y = jax.lax.switch(s_idx, fns, fp, inp)
            return jax.lax.ppermute(y, PIPE_AXIS, fwd_perm), y[:, :C]

        _, ys = jax.lax.scan(tick, jnp.zeros((mb, plan.a_max), jnp.float32),
                             jnp.arange(M + S - 1))
        logits = jnp.where(s_idx == S - 1, ys[S - 1:], 0.0)
        return jax.lax.psum(logits, PIPE_AXIS)

    bspec = _batch_spec(mesh)
    last = DATA_AXIS if plan.fsdp else None
    row_spec = (
        P(PIPE_AXIS, MODEL_AXIS, last) if plan.n_model > 1
        else P(PIPE_AXIS, last)
    )
    sharded = jax.shard_map(
        forward,
        mesh=mesh,
        in_specs=(row_spec, bspec),
        out_specs=bspec,
        check_vma=False,
    )
    return jax.jit(sharded)
