"""Pipeline parallelism over a 'pipe' mesh axis.

The reference has NO pipeline parallelism — its layers execute sequentially
in one process (cnn.c:255-267; SURVEY.md §2 parallelism checklist: "PP:
absent — no stage assignment, no micro-batching"). This module fills that
seam the SPMD way, as a capability beyond reference parity:

- the Sequential's layers are split into S contiguous *stages*, balanced by
  a FLOPs estimate (`make_pipeline_plan`);
- each stage's params are flattened and packed into one row of an
  (S, P_max) array whose leading dim is sharded over the 'pipe' mesh axis —
  every device holds ONLY its stage's weights (1/S of the model, the memory
  property that defines PP);
- one jitted shard_map runs the GPipe schedule: a `lax.scan` over
  M + S - 1 ticks in which every device applies its own stage
  (`lax.switch` on `axis_index('pipe')`), then hands its activations to the
  next stage with `lax.ppermute` — a neighbor transfer that rides ICI by
  mesh construction;
- the loss is computed on the last stage as each microbatch drains, masked
  to zero elsewhere; `jax.grad` differentiates the whole schedule, and the
  transpose of the forward ppermute chain IS the backward pipeline (reverse
  shifts carrying cotangents), so fwd and bwd share one code path.

Composes with DP on a ('pipe', 'data') mesh: the microbatch dim shards over
'data', gradients pmean over 'data' exactly as in dp.py. Stage buffers are
padded to the widest stage (A_max activations, P_max params); padding costs
memory, not FLOPs — the switch branches only compute their real shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.activations import stable_softmax
from ..ops.losses import softmax_cross_entropy, squared_error_total
from .mesh import DATA_AXIS, PIPE_AXIS

TrainState = dict[str, Any]


def _zeros_init(key, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def _layer_cost(layer, in_shape, out_shape, params) -> int:
    """Forward-MAC estimate used to balance stages. Conv: every output
    position reuses the whole kernel; Dense: one MAC per weight; param-free
    layers cost their element count (VPU traffic, negligible next to MXU
    work but keeps ties deterministic)."""
    wsize = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    if not wsize:
        return int(np.prod(in_shape))
    positions = int(np.prod(out_shape[:-1])) if len(out_shape) > 1 else 1
    return wsize * positions


def _partition_balanced(costs: list[int], n_stages: int) -> list[tuple[int, ...]]:
    """Contiguous partition of layer indices into n_stages groups minimizing
    the max group cost (classic linear-partition DP; n is tiny)."""
    n = len(costs)
    if n_stages > n:
        raise ValueError(f"{n_stages} stages > {n} layers")
    prefix = np.concatenate([[0], np.cumsum(costs)])

    def seg(i, j):  # cost of layers [i, j)
        return prefix[j] - prefix[i]

    # best[k][j] = minimal max-cost splitting the first j layers into k groups
    best = np.full((n_stages + 1, n + 1), np.inf)
    cut = np.zeros((n_stages + 1, n + 1), np.int64)
    best[0][0] = 0
    for k in range(1, n_stages + 1):
        for j in range(k, n + 1):
            for i in range(k - 1, j):
                c = max(best[k - 1][i], seg(i, j))
                if c < best[k][j]:
                    best[k][j] = c
                    cut[k][j] = i
    bounds = [n]
    for k in range(n_stages, 0, -1):
        bounds.append(int(cut[k][bounds[-1]]))
    bounds.reverse()
    return [tuple(range(bounds[k], bounds[k + 1])) for k in range(n_stages)]


@dataclasses.dataclass(frozen=True)
class PipelinePlan:
    """Static description of a pipelined model: which layers run on which
    stage, the padded buffer widths, and the flatten/unflatten metadata."""

    model: Any
    n_stages: int
    stage_layers: tuple[tuple[int, ...], ...]
    stage_in_shapes: tuple[tuple[int, ...], ...]  # per-sample input shape per stage
    layer_in_shapes: tuple[tuple[int, ...], ...]  # per-sample input shape per layer
    param_shapes: tuple[tuple[tuple[int, ...], ...], ...]  # per stage: leaf shapes
    param_treedefs: tuple
    num_classes: int
    a_max: int  # flat per-sample activation width crossing any stage boundary
    p_max: int  # padded per-stage flat param length
    backend: str = "xla"
    compute_dtype: Any = None  # per-stage compute cast (e.g. bf16); master
    #   params and the ppermute activation/param buffers stay f32


def make_pipeline_plan(
    model, n_stages: int, *, backend: str = "xla", compute_dtype=None
) -> PipelinePlan:
    """Split `model` (a Sequential) into n_stages balanced stages."""
    key = jax.random.key(0)
    shape = model.input_shape
    layer_in_shapes, costs, zero_params = [], [], []
    for layer in model.layers:
        p, out = layer.init(key, shape, _zeros_init)
        layer_in_shapes.append(tuple(shape))
        costs.append(_layer_cost(layer, shape, out, p))
        zero_params.append(p)
        shape = out
    num_classes = int(shape[-1])
    stage_layers = _partition_balanced(costs, n_stages)

    stage_in_shapes, param_shapes, param_treedefs, p_sizes = [], [], [], []
    boundary_widths = [int(np.prod(model.input_shape))]
    for idxs in stage_layers:
        stage_in_shapes.append(layer_in_shapes[idxs[0]])
        stage_p = [zero_params[i] for i in idxs]
        leaves, treedef = jax.tree.flatten(stage_p)
        param_shapes.append(tuple(tuple(l.shape) for l in leaves))
        param_treedefs.append(treedef)
        p_sizes.append(sum(int(np.prod(l.shape)) for l in leaves))
        end = idxs[-1] + 1
        out_shape = layer_in_shapes[end] if end < len(model.layers) else shape
        boundary_widths.append(int(np.prod(out_shape)))
    return PipelinePlan(
        model=model,
        n_stages=n_stages,
        stage_layers=tuple(stage_layers),
        stage_in_shapes=tuple(stage_in_shapes),
        layer_in_shapes=tuple(layer_in_shapes),
        param_shapes=tuple(param_shapes),
        param_treedefs=tuple(param_treedefs),
        num_classes=num_classes,
        a_max=max(boundary_widths),
        p_max=max(p_sizes) if p_sizes else 1,
        backend=backend,
        compute_dtype=compute_dtype,
    )


def pack_params(plan: PipelinePlan, params) -> jnp.ndarray:
    """Model params (the Sequential's per-layer list) -> (S, P_max) f32 array;
    row s is stage s's leaves raveled and zero-padded."""
    rows = []
    for s, idxs in enumerate(plan.stage_layers):
        leaves = jax.tree.leaves([params[i] for i in idxs])
        flat = (
            jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])
            if leaves
            else jnp.zeros((0,), jnp.float32)
        )
        rows.append(jnp.pad(flat, (0, plan.p_max - flat.shape[0])))
    return jnp.stack(rows)


def unpack_params(plan: PipelinePlan, packed) -> list:
    """(S, P_max) -> the Sequential's per-layer params list (for eval,
    checkpointing, and parity tests against the unpipelined model)."""
    packed = jnp.asarray(packed)
    out: list = [None] * len(plan.model.layers)
    for s, idxs in enumerate(plan.stage_layers):
        stage = _unpack_stage(plan, s, packed[s])
        for i, p in zip(idxs, stage):
            out[i] = p
    return out


def _unpack_stage(plan: PipelinePlan, s: int, flat: jnp.ndarray) -> list:
    leaves, off = [], 0
    for shp in plan.param_shapes[s]:
        size = int(np.prod(shp))
        leaves.append(flat[off:off + size].reshape(shp))
        off += size
    return jax.tree.unflatten(plan.param_treedefs[s], leaves)


def _stage_fns(plan: PipelinePlan, mb: int) -> list[Callable]:
    """One (flat_params, flat_x) -> flat_y function per stage, all with the
    identical (mb, A_max) signature `lax.switch` requires; each branch only
    computes its stage's true shapes."""
    fns = []
    for s, idxs in enumerate(plan.stage_layers):
        in_shape = plan.stage_in_shapes[s]
        in_size = int(np.prod(in_shape))

        def fn(flat_p, flat_x, s=s, idxs=idxs, in_shape=in_shape, in_size=in_size):
            stage_params = _unpack_stage(plan, s, flat_p)
            x = flat_x[:, :in_size].reshape((mb,) + in_shape)
            if plan.compute_dtype is not None:
                x = x.astype(plan.compute_dtype)
                stage_params = jax.tree.map(
                    lambda p: p.astype(plan.compute_dtype), stage_params
                )
            for i, p in zip(idxs, stage_params):
                x = plan.model.layers[i].apply(p, x, backend=plan.backend)
            y = x.reshape(mb, -1).astype(jnp.float32)
            return jnp.pad(y, ((0, 0), (0, plan.a_max - y.shape[1])))

        fns.append(fn)
    return fns


def _make_local_loss(plan: PipelinePlan):
    """The per-device GPipe schedule. Returns local (masked) loss — nonzero
    only on the last stage — so value_and_grad never differentiates through
    a collective; cross-stage gradient flow rides the ppermute transposes."""
    S = plan.n_stages
    C = plan.num_classes
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def local_loss(flat_params, x_mb, y_mb):
        # flat_params: (1, P_max) local row; x_mb: (M, mb, H, W, C) f32;
        # y_mb: (M, mb, C) one-hot.
        fp = flat_params[0]
        M, mb = x_mb.shape[0], x_mb.shape[1]
        fns = _stage_fns(plan, mb)
        s_idx = jax.lax.axis_index(PIPE_AXIS)
        feed = x_mb.reshape(M, mb, -1)
        feed = jnp.pad(feed, ((0, 0), (0, 0), (0, plan.a_max - feed.shape[-1])))

        def tick(carry, t):
            buf, loss_sum, etot_sum, acc_sum = carry
            # Stage 0 ingests microbatch t (clipped past M: those bubbles
            # never reach the last stage inside the scan, so they carry no
            # loss and no gradient); later stages read the shifted buffer.
            inp = jnp.where(s_idx == 0, feed[jnp.minimum(t, M - 1)], buf)
            y = jax.lax.switch(s_idx, fns, fp, inp)
            out_t = t - (S - 1)
            w = jnp.where(
                (s_idx == S - 1) & (out_t >= 0) & (out_t < M), 1.0, 0.0
            )
            logits = y[:, :C]
            yt = y_mb[jnp.clip(out_t, 0, M - 1)]
            loss_sum = loss_sum + w * softmax_cross_entropy(logits, yt)
            probs = stable_softmax(logits)
            etot_sum = etot_sum + w * squared_error_total(probs, yt)
            acc_sum = acc_sum + w * jnp.mean(
                (jnp.argmax(logits, -1) == jnp.argmax(yt, -1)).astype(jnp.float32)
            )
            return (jax.lax.ppermute(y, PIPE_AXIS, fwd_perm),
                    loss_sum, etot_sum, acc_sum), None

        carry0 = (jnp.zeros((mb, plan.a_max), jnp.float32),
                  jnp.float32(0), jnp.float32(0), jnp.float32(0))
        (_, loss_sum, etot_sum, acc_sum), _ = jax.lax.scan(
            tick, carry0, jnp.arange(M + S - 1)
        )
        # Per-microbatch means averaged over microbatches == the full-batch
        # means the unpipelined loss_fn reports (equal microbatch sizes).
        return loss_sum / M, (etot_sum / M, acc_sum / M)

    return local_loss


def _state_specs(state: TrainState, n_stages: int):
    """PartitionSpecs for a PP train state: (S, ...)-leading leaves shard
    over 'pipe' (params + matching optimizer buffers), scalars replicate."""

    def spec(a):
        if getattr(a, "ndim", 0) >= 1 and a.shape[0] == n_stages:
            return P(PIPE_AXIS, *([None] * (a.ndim - 1)))
        return P()

    return jax.tree.map(spec, state)


def make_pp_state(plan: PipelinePlan, params, optimizer, mesh) -> TrainState:
    """Pack + place the train state: stage rows on their pipe coordinate,
    optimizer state created FROM the packed array so its buffers inherit the
    sharding leaf-for-leaf."""
    packed = jax.device_put(
        pack_params(plan, params), NamedSharding(mesh, P(PIPE_AXIS, None))
    )
    return {
        "flat_params": packed,
        "opt_state": optimizer.init(packed),
        "step": jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P())),
    }


def _batch_spec(mesh):
    """Microbatched arrays (M, mb, ...): mb shards over 'data' when the mesh
    has that axis; the microbatch dim is the schedule, never sharded."""
    return P(None, DATA_AXIS) if DATA_AXIS in mesh.axis_names else P(None)


def pp_shard_batch(batch, mesh):
    """Place host (M, mb, ...) microbatch arrays on the mesh."""
    return jax.device_put(batch, NamedSharding(mesh, _batch_spec(mesh)))


def microbatch(x, y, num_microbatches: int):
    """Split a (B, ...) batch into (M, B//M, ...) microbatch arrays."""
    if x.shape[0] % num_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {num_microbatches} microbatches"
        )
    split = lambda a: a.reshape((num_microbatches, -1) + a.shape[1:])
    return split(x), split(y)


def _make_step_body(plan: PipelinePlan, optimizer, has_data: bool):
    """The per-device PP(+DP) train-step body shared by the one-batch step
    and the scanned epoch (the PP twin of dp._make_step_body)."""
    local_loss = _make_local_loss(plan)

    def step(state: TrainState, x_mb, y_mb):
        (loss, (etot, acc)), grads = jax.value_and_grad(
            local_loss, has_aux=True
        )(state["flat_params"], x_mb, y_mb)
        # The masked loss lives on the last stage only: one psum replicates
        # it (and the metric sums) across the pipe.
        loss, etot, acc = (
            jax.lax.psum(m, PIPE_AXIS) for m in (loss, etot, acc)
        )
        if has_data:
            grads = jax.lax.pmean(grads, DATA_AXIS)
            loss, etot, acc = (
                jax.lax.pmean(m, DATA_AXIS) for m in (loss, etot, acc)
            )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["flat_params"]
        )
        flat = optax.apply_updates(state["flat_params"], updates)
        new_state = {"flat_params": flat, "opt_state": opt_state,
                     "step": state["step"] + 1}
        return new_state, {"loss": loss, "etotal": etot, "acc": acc}

    return step


def make_pp_train_step(
    plan: PipelinePlan,
    optimizer: optax.GradientTransformation,
    mesh,
    state: TrainState,
    *,
    donate: bool = True,
):
    """Build the jitted PP(+DP) train step.

    step(state, x_mb, y_mb) -> (state, metrics); x_mb (M, mb, H, W, C) and
    y_mb (M, mb, C) placed via pp_shard_batch. Metrics match the DP/TP
    steps' {loss, etotal, acc} means, so the Trainer can treat all three
    parallel modes uniformly.
    """
    step = _make_step_body(plan, optimizer, DATA_AXIS in mesh.axis_names)
    specs = _state_specs(state, plan.n_stages)
    bspec = _batch_spec(mesh)
    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(specs, bspec, bspec),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_pp_scan_epoch(
    plan: PipelinePlan,
    optimizer: optax.GradientTransformation,
    mesh,
    state: TrainState,
    num_classes: int,
    num_microbatches: int,
    *,
    donate: bool = True,
):
    """Scanned-epoch twin of dp.make_dp_scan_epoch for the pipelined path:
    lax.scan over a batch-index permutation with the uint8 dataset
    device-resident; each scan step microbatches its batch and runs the
    GPipe schedule.

    epoch_fn(state, images_u8, labels_i32, perm) -> (state, metric_sums);
    perm (nsteps, local_batch) with the batch dim sharded over 'data'
    (dp.dp_shard_perm places it); local_batch must be a multiple of
    num_microbatches.
    """
    from ..data.pipeline import PIXEL_SCALE

    has_data = DATA_AXIS in mesh.axis_names
    step = _make_step_body(plan, optimizer, has_data)
    M = num_microbatches

    def epoch(state: TrainState, images, labels, perm):
        def body(state, idx):
            x = images[idx].astype(jnp.float32) / jnp.float32(PIXEL_SCALE)
            y = jax.nn.one_hot(labels[idx], num_classes, dtype=jnp.float32)
            x_mb = x.reshape((M, -1) + x.shape[1:])
            y_mb = y.reshape((M, -1) + y.shape[1:])
            return step(state, x_mb, y_mb)

        state, metrics = jax.lax.scan(body, state, perm)
        return state, jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)

    specs = _state_specs(state, plan.n_stages)
    sharded = jax.shard_map(
        epoch,
        mesh=mesh,
        in_specs=(specs, P(), P(), _batch_spec(mesh)),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_pp_forward(plan: PipelinePlan, mesh):
    """Jitted pipelined forward: (flat_params, x_mb) -> (M, mb, C) logits.
    Runs the same schedule loss-free, collecting each tick's output; the
    last stage's drained ticks are the logits, psum-broadcast to all pipe
    devices (sharded over 'data' if present)."""
    S = plan.n_stages
    C = plan.num_classes
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def forward(flat_params, x_mb):
        fp = flat_params[0]
        M, mb = x_mb.shape[0], x_mb.shape[1]
        fns = _stage_fns(plan, mb)
        s_idx = jax.lax.axis_index(PIPE_AXIS)
        feed = x_mb.reshape(M, mb, -1)
        feed = jnp.pad(feed, ((0, 0), (0, 0), (0, plan.a_max - feed.shape[-1])))

        def tick(buf, t):
            inp = jnp.where(s_idx == 0, feed[jnp.minimum(t, M - 1)], buf)
            y = jax.lax.switch(s_idx, fns, fp, inp)
            return jax.lax.ppermute(y, PIPE_AXIS, fwd_perm), y[:, :C]

        _, ys = jax.lax.scan(tick, jnp.zeros((mb, plan.a_max), jnp.float32),
                             jnp.arange(M + S - 1))
        logits = jnp.where(s_idx == S - 1, ys[S - 1:], 0.0)
        return jax.lax.psum(logits, PIPE_AXIS)

    bspec = _batch_spec(mesh)
    sharded = jax.shard_map(
        forward,
        mesh=mesh,
        in_specs=(P(PIPE_AXIS, None), bspec),
        out_specs=bspec,
        check_vma=False,
    )
    return jax.jit(sharded)
