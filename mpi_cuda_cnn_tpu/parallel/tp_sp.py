"""Tensor parallelism x sequence parallelism for the transformer LM —
Megatron sharding INSIDE the ring-attention shard_map.

The GSPMD LM TP (parallel/tp.py lm_tp_specs) and the shard_map SP step
(parallel/sp.py) cannot compose directly: GSPMD places collectives by
propagation through a jitted global program, while the SP step is an
explicit per-device program. This module writes the Megatron block
explicitly so both axes live in ONE shard_map:

- a ('data'?, 'seq', 'model') mesh: positions shard over 'seq' (ring
  attention rotates k/v blocks exactly as in sp.py — fewer heads per
  device, same schedule), heads/MLP-hidden shard over 'model';
- weights are stored head-structured so plain PartitionSpecs slice them
  cleanly: wqkv (dim, 3, H, hd) and wo (H, hd, dim) put 'model' on the
  H dim (`to_tp_layout`/`from_tp_layout` convert to/from the standard
  tree for checkpoints, eval, and parity tests);
- the classic f/g pair: `_tp_copy` is identity forward / psum-over-
  'model' backward, placed at each parallel region's input (the
  replicated activation is consumed by every model rank, so its true
  cotangent is the SUM of the rank-local ones), and an explicit
  `lax.psum` joins each region's partial outputs before the residual
  add (column-parallel qkv/w1, row-parallel wo/w2 — the pair's
  forward is collective-free in between);
- the loss (final LN + head + CE over the LOCAL sequence shard) is
  computed identically on every model rank from the replicated
  activations, so replicated-leaf gradients arrive exact on every rank
  and sliced-leaf gradients are exact per slice — the step's pmean
  stays over ('data', 'seq') only, exactly as in sp.py.

The reference has neither axis (SURVEY.md §2 checklist, §5.7); this is
the long-context Megatron layout TPU pods actually train with. MoE
blocks compose (round 4): TP runs INSIDE every expert — hidden-sliced
w1/w2, the replicated router entering the region through tp_copy, the
aux loss 1/n_tp-weighted in the differentiated local loss (see
tp_block_apply). Restrictions (checked loudly): heads and kv_heads
divisible by the 'model' axis, dims divisible for w1/w2.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.transformer import TransformerLM, _layernorm
from ..ops.attention import rope
from .mesh import MODEL_AXIS
from ..utils.donation import donate_jit
from .sp import (
    SEQ_AXIS,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)

TrainState = dict[str, Any]


def _make_tp_pair(axis: str):
    """Megatron's f/g pair, BOTH as custom VJPs.

    f (tp_copy): identity forward, psum backward — a replicated
    activation enters a model-parallel region, so its true cotangent is
    the sum of the rank-local ones.
    g (tp_reduce): psum forward, identity backward — the region's
    partial outputs join into the replicated value, whose cotangent
    passes to each rank unchanged.

    g MUST be a custom VJP, not a bare lax.psum: under shard_map's
    manual mode JAX cannot see that psum's output is replicated, so the
    autodiff transpose of psum is ANOTHER psum — which multiplies every
    upstream cotangent by the axis size (measured: every block gradient
    off by exactly that pattern with a bare psum; head/ln_f, downstream
    of the last join, stayed exact)."""

    @jax.custom_vjp
    def tp_copy(x):
        return x

    tp_copy.defvjp(lambda x: (x, None),
                   lambda _, g: (lax.psum(g, axis),))

    @jax.custom_vjp
    def tp_reduce(x):
        return lax.psum(x, axis)

    tp_reduce.defvjp(lambda x: (lax.psum(x, axis), None),
                     lambda _, g: (g,))

    return tp_copy, tp_reduce


def to_tp_layout(params: dict, model: TransformerLM) -> dict:
    """Standard params -> head-structured layout: wqkv (d, 3, H, hd),
    wq (d, H, hd), wkv (d, 2, Hkv, hd), wo (H, hd, d). Pure reshapes —
    bitwise-invertible (from_tp_layout)."""
    d, h, hd, hkv = model.dim, model.heads, model.head_dim, model.n_kv
    out = {k: v for k, v in params.items() if k != "blocks"}
    out["blocks"] = []
    for blk in params["blocks"]:
        b = dict(blk)
        if "wqkv" in b:
            b["wqkv"] = b["wqkv"].reshape(d, 3, h, hd)
        else:
            b["wq"] = b["wq"].reshape(d, h, hd)
            b["wkv"] = b["wkv"].reshape(d, 2, hkv, hd)
        b["wo"] = b["wo"].reshape(h, hd, d)
        out["blocks"].append(b)
    return out


def from_tp_layout(params: dict, model: TransformerLM) -> dict:
    """Inverse of to_tp_layout (for checkpoints/eval/decode)."""
    d, h, hd, hkv = model.dim, model.heads, model.head_dim, model.n_kv
    out = {k: v for k, v in params.items() if k != "blocks"}
    out["blocks"] = []
    for blk in params["blocks"]:
        b = dict(blk)
        if "wqkv" in b:
            b["wqkv"] = b["wqkv"].reshape(d, 3 * h * hd)
        else:
            b["wq"] = b["wq"].reshape(d, h * hd)
            b["wkv"] = b["wkv"].reshape(d, 2 * hkv * hd)
        b["wo"] = b["wo"].reshape(h * hd, d)
        out["blocks"].append(b)
    return out


def tp_block_apply(blk, x, *, attn, rope_pos, w, tp_copy, tp_reduce,
                   moe_top_k: int = 1):
    """One Megatron transformer block on the LOCAL heads/hidden slice.

    Column-parallel qkv projection (each model rank computes H/n_tp
    heads), `attn(q, k, v)` on them, row-parallel wo joined by
    tp_reduce; column-parallel w1 / row-parallel w2 for the MLP. MoE
    blocks run TP INSIDE every expert: the router and dispatch are
    computed identically on every model rank (replicated gate), each
    rank's expert FFN uses its hidden slice (gelu is elementwise on the
    slice), and tp_reduce completes the per-expert partial sums after
    the combine — the same column/row algebra as the dense MLP, per
    expert. The attention callable is the ONLY thing the TP x SP step
    (ring attention over 'seq') and the TP x PP step (full-sequence
    attention per pipeline stage) disagree on — one block
    implementation serves both, so the Megatron math can never drift
    between meshes.

    blk: head-structured leaves (to_tp_layout), already sliced to this
    rank. rope_pos: position ids for rotary (None = learned/absolute,
    applied by the caller). w: the compute-dtype cast.

    Returns (x, aux) — aux the MoE balance loss (0 for dense), computed
    identically on every model rank.
    """
    y = _layernorm(x, blk["ln1"]["g"], blk["ln1"]["b"])
    y = tp_copy(y)
    if "wqkv" in blk:
        qkv = jnp.einsum("bsd,dchx->bschx", y, w(blk["wqkv"]))
        q, k, v = (qkv[:, :, i] for i in range(3))
    else:
        q = jnp.einsum("bsd,dhx->bshx", y, w(blk["wq"]))
        kv = jnp.einsum("bsd,dchx->bschx", y, w(blk["wkv"]))
        k, v = kv[:, :, 0], kv[:, :, 1]
    if rope_pos is not None:
        q = rope(q, rope_pos)
        k = rope(k, rope_pos)
    o = attn(q, k, v)
    part = jnp.einsum("bshx,hxd->bsd", o.astype(x.dtype), w(blk["wo"]))
    x = x + tp_reduce(part)
    y = _layernorm(x, blk["ln2"]["g"], blk["ln2"]["b"])
    y = tp_copy(y)
    if "moe" in blk:
        from .ep import moe_mlp

        b, s, d = y.shape
        moe_p = jax.tree.map(w, blk["moe"])
        # The gate is replicated but consumed INSIDE the parallel
        # region: its combine-path cotangents are rank-partial (each
        # rank weights its own expert-output slice), so like any
        # region input it must enter through tp_copy — the psum in
        # backward assembles the full gradient. The aux path is the
        # exception (computed identically on every rank); the CALLER
        # accounts for it by weighting aux with 1/n_tp in the local
        # loss so the same psum restores exactly one contribution
        # (see make_tp_sp_lm_train_step / tp_pp_lm).
        moe_p["gate"] = tp_copy(moe_p["gate"])
        # axis=None: dispatch over the LOCAL tokens with the full
        # (replicated) gate; w1/w2 hold the hidden SLICE, so the
        # combine's output is this rank's partial sum.
        part, aux = moe_mlp(
            y.reshape(b * s, d), moe_p,
            n_experts=moe_p["w1"].shape[0], top_k=moe_top_k, axis=None,
        )
        return x + tp_reduce(part.reshape(b, s, d).astype(x.dtype)), aux
    part = jax.nn.gelu(y @ w(blk["w1"])) @ w(blk["w2"])
    return x + tp_reduce(part), jnp.float32(0)


def _check_tp_sp(model: TransformerLM, n_tp: int) -> None:
    if model.heads % n_tp or model.n_kv % n_tp:
        raise ValueError(
            f"the model-axis size {n_tp} must divide both heads "
            f"{model.heads} and kv_heads {model.n_kv}"
        )
    if (4 * model.dim) % n_tp:
        raise ValueError(
            f"MLP hidden {4 * model.dim} not divisible by model-axis "
            f"size {n_tp}"
        )


# 'model' placement per head-structured block leaf — THE single table of
# which weights are Megatron-sliced and on which dim. tp_sp_param_specs
# consumes it directly; the TP x PP module (parallel/tp_pp_lm.py)
# prepends the stacked-block 'pipe' dim to the same tuples, so a new or
# reshaped sliced leaf added here automatically shards (and norm-counts)
# correctly on BOTH meshes.
TP_SPEC_TAILS = {
    "wqkv": (None, None, MODEL_AXIS, None),
    "wq": (None, MODEL_AXIS, None),
    "wkv": (None, None, MODEL_AXIS, None),
    "wo": (MODEL_AXIS, None, None),
    "w1": (None, MODEL_AXIS),
    "w2": (MODEL_AXIS, None),
}

# MoE block leaves (under blk["moe"]): TP INSIDE every expert — w1
# (E, d, 4d) column-parallel on hidden, w2 (E, 4d, d) row-parallel, the
# router gate replicated (dispatch is computed identically on every
# model rank). The gelu is elementwise on the hidden slice, so each
# rank's expert FFN produces a partial sum the caller's tp_reduce
# completes — the exact dense-MLP Megatron trick, per expert.
MOE_SPEC_TAILS = {
    "w1": (None, None, MODEL_AXIS),
    "w2": (None, MODEL_AXIS, None),
}


def tp_sp_param_specs(model: TransformerLM, params_tp: dict) -> dict:
    """PartitionSpecs for the head-structured tree: 'model' on the H dim
    of wqkv/wq/wkv/wo, on w1's columns and w2's rows; all else
    replicated (the 'seq'/'data' axes never shard parameters)."""
    spec_map = {k: P(*t) for k, t in TP_SPEC_TAILS.items()}
    moe_map = {k: P(*t) for k, t in MOE_SPEC_TAILS.items()}

    def blk_spec(k, v):
        if k == "moe":
            return {mk: moe_map.get(mk, jax.tree.map(lambda _: P(), mv))
                    for mk, mv in v.items()}
        return spec_map.get(k, jax.tree.map(lambda _: P(), v))

    out = {k: jax.tree.map(lambda _: P(), v)
           for k, v in params_tp.items() if k != "blocks"}
    out["blocks"] = [
        {k: blk_spec(k, v) for k, v in blk.items()}
        for blk in params_tp["blocks"]
    ]
    return out


def make_tp_sp_state(model: TransformerLM, params, optimizer, mesh
                     ) -> tuple[TrainState, Any]:
    """Head-structured, model-sliced train state; optimizer buffers
    inherit the shardings leaf-for-leaf."""
    _check_tp_sp(model, mesh.shape[MODEL_AXIS])
    params_tp = to_tp_layout(params, model)
    state = {
        "params": params_tp,
        "opt_state": optimizer.init(params_tp),
        "step": jnp.zeros((), jnp.int32),
    }

    # Specs for the whole state: params get the structured specs; the
    # optimizer tree mirrors the params tree leaf-for-leaf (optax), so
    # the same specs apply by path; scalars replicate.
    pspecs = tp_sp_param_specs(model, params_tp)

    def state_specs(st):
        leaves, treedef = jax.tree_util.tree_flatten_with_path(st)
        # Pair each param's FULL path-in-params with (spec, exact shape):
        # opt_state nests the params tree under transformation wrappers,
        # so a params-mirroring buffer's path ends with the full param
        # path. Requiring the exact shape too (not rank, the old
        # heuristic) means a wrapper's own buffer can only be mis-specced
        # if it aliases BOTH the complete path suffix and the shape of a
        # param — at which point it is that param's mirror in all but
        # name (advisor r3: suffix+ndim could sliver-match e.g. a
        # same-rank buffer nested under a 'blocks'/'w1'-like key).
        params_flat = jax.tree_util.tree_flatten_with_path(
            params_tp
        )[0]
        spec_flat = jax.tree_util.tree_flatten_with_path(
            pspecs, is_leaf=lambda x: isinstance(x, P)
        )[0]
        pspec_flat = {
            tuple(str(getattr(p, "key", getattr(p, "idx", p)))
                  for p in ppath): (s, tuple(pleaf.shape))
            for (ppath, pleaf), (_, s) in zip(params_flat, spec_flat,
                                              strict=True)
        }

        def spec_for(path, leaf):
            keys = tuple(
                str(getattr(p, "key", getattr(p, "idx", p))) for p in path
            )
            for k, (s, shp) in pspec_flat.items():
                if keys[-len(k):] == k and \
                        tuple(getattr(leaf, "shape", ())) == shp:
                    return s
            return P()

        return jax.tree_util.tree_unflatten(
            treedef, [spec_for(p, l) for p, l in leaves]
        )

    specs = state_specs(state)
    return jax.device_put(
        state,
        jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                     is_leaf=lambda x: isinstance(x, P)),
    ), specs


def make_tp_sp_lm_train_step(
    model: TransformerLM,
    optimizer: optax.GradientTransformation,
    mesh,
    state_specs,
    *,
    data_axis: str | None = None,
    compute_dtype=None,
    remat: bool = False,
    donate: bool = True,
    ce_chunk: int = 0,
    impl: str = "ring",
    grad_clip: float = 0.0,
    moe_aux_weight: float = 0.01,
):
    """Jitted Megatron x ring train step.

    step(state, tokens, targets) -> (state, {"loss": ...}); tokens (B, S)
    sharded (data?, seq) like the plain SP step. Inside: ring attention
    over 'seq' with H/n_tp local heads (`impl="ring_flash"` folds each
    hop with the fused Pallas flash kernel — the on-chip configuration;
    needs 128-aligned per-shard sequences like the plain SP step),
    column/row-parallel matmuls over 'model' with the f/psum pair, loss
    on the local sequence shard. MoE blocks run TP inside every expert
    (tp_block_apply) with shard-local dispatch — the same estimator as
    every sharded MoE trainer.
    """
    _check_tp_sp(model, mesh.shape[MODEL_AXIS])
    if impl == "ring":
        attn_body = ring_attention
    elif impl == "ring_flash":
        attn_body = ring_flash_attention
    elif impl == "ulysses":
        # Ulysses all-to-alls the LOCAL (already TP-sliced) heads across
        # 'seq': each device ends with the full sequence for
        # H/(n_tp*n_seq) heads — both axes shard the head dim.
        attn_body = ulysses_attention
        n_tp = mesh.shape[MODEL_AXIS]
        local_heads = model.heads // n_tp
        if local_heads % mesh.shape[SEQ_AXIS]:
            raise ValueError(
                f"impl='ulysses' under TP x SP needs the TP-local heads "
                f"({model.heads}/{n_tp} = {local_heads}) divisible by "
                f"the seq-axis size {mesh.shape[SEQ_AXIS]}; use ring"
            )
    else:
        raise ValueError(
            f"unknown TP x SP impl {impl!r}; 'ring', 'ring_flash', or "
            "'ulysses'"
        )
    n_seq = mesh.shape[SEQ_AXIS]
    n_tp = mesh.shape[MODEL_AXIS]
    reduce_axes = tuple(a for a in (data_axis, SEQ_AXIS) if a)
    cd = compute_dtype
    tp_copy, tp_reduce = _make_tp_pair(MODEL_AXIS)

    def local_loss(params, tokens, targets):
        b, s_local = tokens.shape
        if s_local * n_seq > model.max_seq:
            raise ValueError(
                f"global sequence {s_local * n_seq} exceeds "
                f"max_seq {model.max_seq}"
            )
        if impl == "ring_flash" and s_local % 128:
            # Fail with GLOBAL context — the kernel's own check would
            # name only the confusing shard-local length (same guard as
            # the plain SP step, parallel/sp.py).
            raise ValueError(
                f"impl='ring_flash' needs the per-shard sequence to be a"
                f" multiple of 128 (flash block granularity): global"
                f" S={s_local * n_seq} over seq={n_seq} devices gives"
                f" s_local={s_local}"
            )
        w = (lambda t: t.astype(cd)) if cd else (lambda t: t)
        hd = model.head_dim
        pos = lax.axis_index(SEQ_AXIS) * s_local + jnp.arange(s_local)

        x = params["tok_emb"][tokens]
        if model.pos == "learned":
            x = x + params["pos_emb"][pos][None, :, :]
        x = w(x)

        def block(blk, x):
            # Ring attention over 'seq' on the local heads; Megatron
            # column/row regions live in the shared block applier.
            return tp_block_apply(
                blk, x,
                attn=lambda q, k, v: attn_body(
                    q, k, v, axis=SEQ_AXIS, causal=True
                ),
                rope_pos=pos if model.pos == "rope" else None,
                w=w, tp_copy=tp_copy, tp_reduce=tp_reduce,
                moe_top_k=model.moe_top_k,
            )

        if remat:
            block = jax.checkpoint(block)
        aux_total = jnp.float32(0)
        for blk in params["blocks"]:
            x, aux = block(blk, x)
            aux_total = aux_total + aux
        feats = _layernorm(x, params["ln_f"]["g"], params["ln_f"]["b"])
        if ce_chunk:
            from ..ops.losses import chunked_ce_mean

            nll_term = chunked_ce_mean(
                feats, params["head"], targets, ce_chunk, cd
            )
        else:
            logits = jnp.matmul(
                feats, w(params["head"]),
                preferred_element_type=jnp.float32
            )
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
            nll_term = jnp.mean(nll)
        # MoE aux enters the DIFFERENTIATED loss at weight/n_tp: every
        # upstream activation/param reaches it through a tp_copy whose
        # backward psums over 'model', and the aux is computed
        # identically on every rank — 1/n_tp makes the psum restore
        # exactly one contribution. The METRIC gets the missing
        # (1 - 1/n_tp) share added back outside the grad (step below).
        return nll_term + (moe_aux_weight / n_tp) * aux_total, aux_total

    # The global gradient norm must count each logical parameter exactly
    # once: psum the sliced leaves' squared norms over 'model', add the
    # replicated leaves' once. The classification lives in the ONE
    # shared helper (train/optimizer.split_grad_sq) and is derived from
    # the very PartitionSpecs the step shards with, so it can never
    # drift from the other sharded-param meshes'.
    def _global_grad_sq(grads):
        from ..train.optimizer import split_grad_sq

        sliced, rep = split_grad_sq(grads, state_specs["params"],
                                    MODEL_AXIS)
        return lax.psum(sliced, MODEL_AXIS) + rep

    def step(state, tokens, targets):
        (loss, aux), grads = jax.value_and_grad(
            local_loss, has_aux=True
        )(state["params"], tokens, targets)
        # The metric gets the aux share the 1/n_tp grad-weighting left
        # out — the reported loss equals nll + moe_aux_weight * aux
        # exactly (aux is replicated across 'model').
        loss = loss + moe_aux_weight * (1.0 - 1.0 / n_tp) * aux
        # Sliced leaves: exact per slice. Replicated leaves: identical on
        # every model rank (the loss consumed replicated activations).
        # Only the data/seq shards hold DIFFERENT samples -> pmean there,
        # never over 'model' (it would average unrelated slices).
        grads = jax.tree.map(lambda g: lax.pmean(g, reduce_axes), grads)
        loss = lax.pmean(loss, reduce_axes)
        if grad_clip > 0:
            # The CROSS-RANK norm is identical on every rank (psum +
            # replicated sums), so the clip scale is too.
            from ..train.optimizer import clip_grads_by_global_sq

            grads = clip_grads_by_global_sq(
                grads, _global_grad_sq(grads), grad_clip
            )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return (
            {"params": params, "opt_state": opt_state,
             "step": state["step"] + 1},
            {"loss": loss},
        )

    bspec = P(data_axis, SEQ_AXIS) if data_axis else P(None, SEQ_AXIS)
    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(state_specs, bspec, bspec),
        out_specs=(state_specs, P()),
        check_vma=False,
    )
    return donate_jit(sharded, donate=donate)
