"""Data parallelism over the mesh — the reference's only parallelism
strategy (SURVEY.md §2.6), built the SPMD way.

Reference behavior being replaced (cnnmpi.c:456-499): contiguous shard per
rank, then per sample and per layer a blocking in-place
MPI_Allreduce(SUM) of a scratch buffer — whose result is never even
consumed (bug 2.6a), alongside a spurious weight decay (2.6b) and divergent
per-rank init that is never synchronized (2.6c). What we implement is the
*intent*: synchronous gradient-averaging data parallelism —

- params initialized once and replicated (fixes 2.6c: one keyed init, no
  per-rank seeds),
- each device computes grads on its batch shard,
- ONE `lax.pmean` of the whole grad pytree per step (XLA fuses this into a
  single ICI all-reduce; vs the reference's per-layer per-sample storm),
- every device applies the identical optimizer update (fixes 2.6a/b).

Expressed with `jax.shard_map` so the collective is explicit and the mesh
axis extensible ('model' axis for TP slots into the same specs).
"""

from __future__ import annotations

from collections.abc import Callable
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..data.pipeline import PIXEL_SCALE
from ..obs.trace import annotate
from .mesh import DATA_AXIS
from ..utils.donation import donate_jit

TrainState = dict[str, Any]  # {"params": pytree, "opt_state": pytree, "step": i32}


def replicate(tree, mesh):
    """Place a host pytree on the mesh fully replicated (the synchronized
    initial broadcast the reference forgot, SURVEY.md 2.6c)."""
    return jax.device_put(tree, NamedSharding(mesh, P()))


def dp_shard_batch(batch, mesh, axis: str = DATA_AXIS):
    """Place a host batch on the mesh sharded along its leading dim."""
    return jax.device_put(batch, NamedSharding(mesh, P(axis)))


def dp_shard_perm(perm, mesh, axis: str = DATA_AXIS):
    """Place a (nsteps, batch) permutation on the mesh with the batch dim
    sharded — the host-side twin of the scan-epoch perm in_specs
    (P(None, axis)); keep the two in sync here, in one place. On a mesh
    without the data axis (e.g. pipe-only PP), the perm is replicated,
    matching pp.make_pp_scan_epoch's P(None) spec."""
    spec = P(None, axis) if axis in mesh.axis_names else P(None)
    return jax.device_put(perm, NamedSharding(mesh, spec))


def _local_grads(loss_fn: Callable, params, x, y, grad_accum: int,
                 accum_dtype=None):
    """(loss, aux, grads) on the local shard, optionally accumulated over
    `grad_accum` sequential micro-batches (lax.scan keeps ONE micro-batch
    of activations live — the memory half of the reference's 32-sample
    accumulator semantics, cnn.c:467-469, generalized).

    accum_dtype (e.g. jnp.bfloat16) stores the gradient ACCUMULATOR in
    that dtype — half the grad-tree bytes per scan iteration IF the
    carry is a real HBM pass. Measured on the v5e flagship it is NOT:
    XLA fuses the accumulate into the backward's epilogue, so bf16
    carry ties f32 (876 vs 871 ms at accum 8 — PERF.md flagship
    section records the non-win so nobody re-derives it). The flag
    stays for backends/shapes where that fusion doesn't hold; default
    None keeps exact f32 accumulation. The mean is cast back to the
    param dtype before the optimizer. Accuracy when on: summing N bf16
    micro-grads loses ~sqrt(N)*2^-8 relative (~1-2% at N=16-32) — the
    same error class as bf16 gradient all-reduce, bounded by test.
    Loss/aux always accumulate f32 (scalars — free)."""

    if grad_accum <= 1:
        accum_dtype = None  # no accumulator, no traffic to save — and a
        #                     cast round-trip would only lose precision

    def compute(px, py):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, px, py
        )
        if accum_dtype is not None:
            grads = jax.tree.map(lambda g: g.astype(accum_dtype), grads)
        return loss, aux, grads

    if grad_accum <= 1:
        return compute(x, y)
    a = grad_accum
    # Interleaved split (micro i takes rows i, a+i, 2a+i, ...) rather than
    # contiguous blocks: under GSPMD (TP/FSDP) the batch dim is sharded in
    # contiguous device blocks, and a contiguous micro-split would give
    # each micro-batch to ONE device, forcing a full resharding per scan
    # step. The strided split keeps every micro-batch evenly spread across
    # shards (reshape/transpose preserve the dim-0 sharding); the mean
    # over micro-batches is partition-independent, so the math is
    # unchanged either way.
    def split(t):
        return t.reshape(t.shape[0] // a, a, *t.shape[1:]).swapaxes(0, 1)

    xs, ys = split(x), split(y)
    # Accumulator traffic accounting (profile_lm --grad-accum-ablation
    # attributes it; PERF.md "grad-accum overhead"): per micro-batch the
    # carry costs one grad-tree read + write (~5.4 GB at the 679.5M
    # flagship ≈ the fitted ~8 ms/microbatch), which is the floor of
    # true accumulation — XLA fuses the add into the backward's
    # epilogue (the measured bf16-carry tie, PERF.md), the whole-state
    # donation aliases the carry in place, and `accum_dtype` halves the
    # bytes where that fusion doesn't hold. A first-micro-batch carry
    # seed (peeling iteration 0 out of the scan) was tried and REVERTED:
    # it duplicates the fwd+bwd body in the compiled program (code size,
    # compile time) and double-counts every static-body cost record for
    # one zeros-write saved per STEP — per-step, not per-microbatch, so
    # it cannot touch the 8 ms term.
    shapes = jax.eval_shape(compute, xs[0], ys[0])
    zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    totals, _ = jax.lax.scan(
        lambda c, xy: (jax.tree.map(jnp.add, c, compute(*xy)), None),
        zeros,
        (xs, ys),
    )
    loss, aux, grads = jax.tree.map(lambda t: t / a, totals)
    if accum_dtype is not None:
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
    return loss, aux, grads


def local_grads_no_aux(loss_fn, params, x, y, grad_accum: int,
                       accum_dtype=None):
    """(loss, grads) for an aux-free scalar loss_fn(params, x, y) —
    the one shim over _local_grads the LM steps share (train/lm.py,
    parallel/sp.py, parallel/ep.py) instead of each faking an aux."""

    loss, _, grads = _local_grads(
        lambda p, a, b: (loss_fn(p, a, b), jnp.float32(0)),
        params, x, y, grad_accum, accum_dtype=accum_dtype,
    )
    return loss, grads


def _make_step_body(
    loss_fn: Callable,
    optimizer,
    axis: str,
    augment=None,
    aug_seed: int = 0,
    grad_accum: int = 1,
    elastic_width: int = 0,
    axis_size: int = 1,
):
    """The per-step SPMD body shared by the one-batch step and the scanned
    epoch: local grads, ONE fused gradient all-reduce, identical update on
    every device.

    `augment` (data/augment.py) runs on-device on the normalized shard,
    keyed by (step, data-axis index) so every device and every step draws
    independent transforms, and a resumed run (step restored from a
    checkpoint) replays the same stream.

    elastic_width > 0 swaps the local-mean + pmean gradient for the
    width-invariant canonical-tree reduction (parallel/elastic.py): the
    update — and therefore the whole trajectory — is bitwise identical
    on any power-of-two data-axis width with >= 2 canonical microbatches
    per device, which is what makes a preempted run resumable on a
    different topology (ISSUE 5). On that path the augment key folds in
    the GLOBAL canonical-shard index, not the device rank, so the pixel
    stream is width-invariant too.
    """

    def elastic_step(state: TrainState, x, y):
        from .elastic import elastic_grads

        def grad_fn(px, py):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state["params"], px, py)
            return loss, aux, grads

        prepare = None
        if augment is not None:
            def prepare(px, py, shard_idx):
                key = jax.random.fold_in(
                    jax.random.key(aug_seed), state["step"]
                )
                key = jax.random.fold_in(key, shard_idx)
                return augment(key, px), py

        with annotate("dp.elastic_grads"):
            # Every metric make_loss_fn returns is mean-semantics
            # (loss, acc, and etotal — squared_error_total divides by
            # its batch size), so the mean over canonical microbatches
            # keeps every metric on the plain step's scale
            # (test_elastic_metrics_match_plain_scale pins it).
            loss, aux, grads = elastic_grads(
                grad_fn, x, y, elastic_width=elastic_width, axis=axis,
                axis_size=axis_size, prepare=prepare,
            )
        with annotate("dp.update"):
            updates, opt_state = optimizer.update(
                grads, state["opt_state"], state["params"]
            )
            params = optax.apply_updates(state["params"], updates)
        return (
            {"params": params, "opt_state": opt_state,
             "step": state["step"] + 1},
            {"loss": loss, **aux},
        )

    def step(state: TrainState, x, y):
        if augment is not None:
            with annotate("dp.augment"):
                key = jax.random.fold_in(jax.random.key(aug_seed), state["step"])
                key = jax.random.fold_in(key, jax.lax.axis_index(axis))
                x = augment(key, x)
        with annotate("dp.local_grads"):
            loss, aux, grads = _local_grads(
                loss_fn, state["params"], x, y, grad_accum
            )
        # ONE fused gradient all-reduce per step — the explicit SPMD twin
        # of the reference's intent, replacing its per-sample-per-layer
        # allreduce storm (cnnmpi.c:490). XLA fuses the pytree of pmeans
        # into a single ICI collective.
        with annotate("dp.grad_allreduce"):
            grads = jax.lax.pmean(grads, axis)
            loss = jax.lax.pmean(loss, axis)
            aux = jax.lax.pmean(aux, axis)
        with annotate("dp.update"):
            updates, opt_state = optimizer.update(
                grads, state["opt_state"], state["params"]
            )
            params = optax.apply_updates(state["params"], updates)
        new_state = {"params": params, "opt_state": opt_state,
                     "step": state["step"] + 1}
        return new_state, {"loss": loss, **aux}

    return elastic_step if elastic_width else step


def make_dp_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh,
    *,
    axis: str = DATA_AXIS,
    donate: bool = True,
    augment=None,
    aug_seed: int = 0,
    grad_accum: int = 1,
    elastic_width: int = 0,
):
    """Build the jitted DP train step.

    loss_fn(params, x, y) -> (scalar loss, aux dict); x/y are the
    per-device shard inside shard_map. Returns step(state, x, y) ->
    (state, metrics) with state replicated and batches sharded on `axis`.
    elastic_width > 0 selects the width-invariant gradient reduction
    (see _make_step_body / parallel/elastic.py).
    """
    step = _make_step_body(loss_fn, optimizer, axis, augment, aug_seed,
                           grad_accum, elastic_width,
                           mesh.shape.get(axis, 1))

    # check_vma=False: collective typing stays classic/explicit (local grads
    # until the pmean above). Also required for Pallas interpreter-mode
    # kernels, which cannot evaluate under the varying-axes tracer.
    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return donate_jit(sharded, donate=donate)


def make_dp_scan_epoch(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh,
    num_classes: int,
    *,
    axis: str = DATA_AXIS,
    donate: bool = True,
    augment=None,
    aug_seed: int = 0,
    grad_accum: int = 1,
    elastic_width: int = 0,
):
    """Build a jitted many-steps-per-dispatch trainer: the whole (chunk of
    an) epoch is ONE `lax.scan` over a batch-index permutation, with the raw
    uint8 training set resident in HBM.

    The reference pays a host round-trip per sample (cnn.c:451-474); the
    per-batch jit step still pays one dispatch per batch, which dominates at
    this model size. Here the host sends only an int32 permutation per
    epoch; normalization (cnn.c:457) and one-hot (cnn.c:462-464) happen
    on-device inside the scan body, so HBM holds pixels as uint8.

    epoch_fn(state, images_u8, labels_i32, perm) -> (state, metric_sums)
      images: (N,H,W,C) uint8, replicated.  labels: (N,) int32, replicated.
      perm:   (nsteps, batch) int32, batch dim sharded on `axis`.
      metric_sums: metrics summed over the scanned steps.
    """
    step = _make_step_body(loss_fn, optimizer, axis, augment, aug_seed,
                           grad_accum, elastic_width,
                           mesh.shape.get(axis, 1))

    def epoch(state: TrainState, images, labels, perm):
        def body(state, idx):
            x = images[idx].astype(jnp.float32) / jnp.float32(PIXEL_SCALE)
            y = jax.nn.one_hot(labels[idx], num_classes, dtype=jnp.float32)
            return step(state, x, y)

        state, metrics = jax.lax.scan(body, state, perm)
        return state, jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics)

    sharded = jax.shard_map(
        epoch,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(None, axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return donate_jit(sharded, donate=donate)


def make_dp_eval_step(predict_fn: Callable, mesh, *, axis: str = DATA_AXIS):
    """Sharded forward pass: predict_fn(params, x) -> per-shard outputs,
    gathered back to a full batch (the reference gates eval to rank 0
    instead, cnnmpi.c:521 — here every device works on its shard)."""

    def step(params, x):
        return predict_fn(params, x)

    sharded = jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(axis)), out_specs=P(axis),
        check_vma=False,
    )
    return jax.jit(sharded)
