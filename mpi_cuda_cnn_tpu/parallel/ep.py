"""Expert parallelism (MoE) over an 'expert' mesh axis.

The reference has no MoE/routing of any kind (SURVEY.md §2 parallelism
checklist: "EP: absent") — like sequence parallelism, this is a
first-class capability of the framework rather than a parity item, and it
completes the parallelism family: DP (dp.py), TP (tp.py), PP (pp.py),
SP (sp.py), EP (here).

Design — Switch-style top-1 routing with static shapes (XLA needs them):

- Gating: per-token softmax over experts, top-1 expert, gate = its prob.
- Capacity: each expert accepts at most C tokens per device shard
  (C = ceil(T/E * capacity_factor)); overflow tokens are DROPPED (their
  MoE output is 0, the residual connection carries them — standard
  Switch behavior) via position-in-expert cumsum masking.
- Dispatch/combine are dense one-hot tensors (T, E, C) contracted with
  einsum — the MXU-friendly formulation (no scatter/gather).
- EP: experts shard over the 'expert' axis; a tiled all_to_all turns the
  per-device (E, C, D) dispatch buffer into (E/P, P*C, D) — each device
  holds ALL tokens routed to ITS experts — the experts run as one batched
  einsum, and the inverse all_to_all returns outputs to the tokens'
  owners. Two collectives per layer, exactly like the reference
  frameworks this pattern comes from, riding ICI here.

`moe_mlp` is the SPMD body (callable inside shard_map, or standalone with
axis=None for the single-device oracle the tests compare against).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..obs.trace import annotate
from ..utils.donation import donate_jit

EXPERT_AXIS = "expert"


def init_moe_params(key, dim: int, hidden: int, n_experts: int) -> dict:
    """Gate + expert-stacked MLP weights. Experts are stacked on a leading
    dim so they shard/slice cleanly: w1 (E, D, H), w2 (E, H, D)."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / jnp.sqrt(jnp.asarray(dim, jnp.float32))
    scale_hid = 1.0 / jnp.sqrt(jnp.asarray(hidden, jnp.float32))
    return {
        "gate": jax.random.normal(k1, (dim, n_experts), jnp.float32) * scale_in,
        "w1": jax.random.normal(k2, (n_experts, dim, hidden), jnp.float32) * scale_in,
        "w2": jax.random.normal(k3, (n_experts, hidden, dim), jnp.float32) * scale_hid,
    }


def router_dispatch(x, gate_w, n_experts: int, capacity: int, k: int = 1,
                    dtype=None, return_stats: bool = False):
    """THE routing core — top-k choice + capacity slot assignment, fused.

    Builds the ONE (T, E, C) dispatch tensor the MoE einsums consume,
    DIRECTLY in `dtype` (default x.dtype): the (T, E, C) writes are the
    dominant routing cost (2.7 GB/layer at the profiled T=16k config,
    PERF.md "MoE single-chip attribution"), and the old f32-build +
    cast + separate combine tensor paid that cost four ways — f32 build,
    cast read+write, second (combine) build per choice, second cast.
    The gate weighting now travels as a (T, E) map instead of a second
    (T, E, C) tensor: each token's chosen experts are DISTINCT (lax.top_k),
    so at most one choice lands on any (t, e) pair and
    combine == dispatch * gate_te[:, :, None] exactly.

    All queue math (cumsum positions, capacity masks) stays f32 — exact
    small-integer arithmetic, which bf16 loses past 256 tokens; only the
    (T, E, C) outer products take `dtype`.

    k=1 is Switch routing (raw top prob as the gate); k>1 renormalizes
    over the chosen k (GShard). Capacity is allocated by CHOICE
    PRIORITY: all tokens' 1st choices claim slots before any 2nd choice
    does, so adding k > 1 never evicts a would-be top-1 assignment. Per
    choice, slots go in token order.

    Returns (dispatch, gate_te, aux_loss):
      dispatch: (T, E, C) in {0, 1}, `dtype` — token t occupies slot c
                of expert e;
      gate_te:  (T, E) f32 — the token's (renormalized) gate for each
                chosen-and-kept expert, 0 elsewhere;
      aux_loss: scalar f32 load-balancing loss (Switch form over FIRST
                choices: the signal that spreads primary assignments).

    return_stats=True swaps aux_loss for its PER-EXPERT SUFFICIENT
    STATISTICS (first_choice_count (E,), prob_sum (E,)) — additive
    across token chunks, so moe_mlp's chunked scan can accumulate them
    in the carry and form the balance loss ONCE GLOBALLY (a mean of
    per-chunk losses is a different, biased objective: the product of
    per-chunk means is not the mean of the product).
    """
    t = x.shape[0]
    dtype = jnp.dtype(dtype) if dtype is not None else x.dtype
    logits = x @ gate_w                                   # (T, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    vals, idx = jax.lax.top_k(probs, k)                   # (T, k), distinct
    gates = vals if k == 1 else vals / jnp.sum(vals, axis=-1, keepdims=True)

    dispatch = jnp.zeros((t, n_experts, capacity), dtype)
    gate_te = jnp.zeros((t, n_experts), jnp.float32)
    used = jnp.zeros((n_experts,), jnp.float32)  # kept slots per expert
    # Python loop over choices: unrolled at trace time, so the compiled
    # program grows linearly in k. Fine for the MoE regimes this routing
    # targets (k is 1 or 2 in every shipped config; even 4 is cheap).
    for j in range(k):
        onehot = jax.nn.one_hot(idx[:, j], n_experts, dtype=jnp.float32)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0 + used[None, :]) * onehot
        keep = (pos < capacity).astype(jnp.float32) * onehot
        slot = jax.nn.one_hot(
            jnp.sum(pos * onehot, axis=-1).astype(jnp.int32), capacity,
            dtype=dtype,
        )
        dispatch = dispatch + keep.astype(dtype)[:, :, None] * slot[:, None, :]
        gate_te = gate_te + keep * gates[:, j, None]
        used = used + jnp.sum(keep, axis=0)
    onehot1 = jax.nn.one_hot(idx[:, 0], n_experts, dtype=jnp.float32)
    if return_stats:
        return dispatch, gate_te, (jnp.sum(onehot1, axis=0),
                                   jnp.sum(probs, axis=0))
    aux_loss = jnp.sum(
        jnp.mean(onehot1, axis=0) * jnp.mean(probs, axis=0)
    ) * n_experts
    return dispatch, gate_te, aux_loss


def top1_dispatch(x, gate_w, n_experts: int, capacity: int):
    """Switch top-1 routing for tokens x: (T, D) — the dense-tensor view
    of router_dispatch (kept for callers/tests that want the classic
    (dispatch, combine) pair; the hot path consumes router_dispatch's
    fused form and never builds `combine`).

    Returns (dispatch, combine, aux_loss):
      dispatch: (T, E, C) f32 in {0, 1} — token t occupies slot c of
                expert e (at most one nonzero per token);
      combine:  (T, E, C) f32 — dispatch weighted by the token's gate;
      aux_loss: scalar load-balancing loss (mean_prob · mean_assignment
                · E, the Switch auxiliary), to be added by the caller.
    """
    return topk_dispatch(x, gate_w, n_experts, capacity, k=1)


def topk_dispatch(x, gate_w, n_experts: int, capacity: int, k: int = 2):
    """Top-k routing (GShard-style) for tokens x: (T, D) — dense-tensor
    view of router_dispatch; see top1_dispatch. k=1 reproduces
    top1_dispatch exactly (tested)."""
    dispatch, gate_te, aux = router_dispatch(
        x, gate_w, n_experts, capacity, k=k, dtype=jnp.float32
    )
    # combine == dispatch * gate_te exactly: the chosen experts per token
    # are distinct, so each (t, e) pair carries at most one choice's gate.
    combine = dispatch * gate_te[:, :, None]
    return dispatch, combine, aux


def _expert_ffn(h, w1, w2):
    """Batched expert MLP: h (E_local, S, D) x w1 (E_local, D, H) ..."""
    return jnp.einsum("esh,ehd->esd", jax.nn.relu(jnp.einsum("esd,edh->esh", h, w1)), w2)


def moe_mlp(
    x,
    params: dict,
    *,
    n_experts: int,
    capacity_factor: float = 1.25,
    axis: str | None = EXPERT_AXIS,
    top_k: int = 1,
    dispatch_chunk: int = 0,
    dispatch_dtype=None,
    _aux_stats: bool = False,
):
    """MoE MLP for x: (T, D) local tokens. SPMD body when `axis` names a
    mesh axis — then params["w1"]/["w2"] hold only THIS device's E/P
    expert stack (sharded on their leading dim; the gate is replicated) —
    or the exact single-device dense oracle when axis=None (full stacks).
    top_k=1 is Switch routing; top_k=2 the GShard form (capacity scales
    with k so per-expert slots track the k*T total assignments).
    Returns (y: (T, D), aux_loss: scalar).

    dispatch_chunk > 0 routes tokens in fixed-size chunks (a lax.scan
    sharing the expert weights) — the single-chip MoE throughput lever.
    The dense (T, E, C) dispatch/combine einsums cost 2*E*C*T*D with
    C = ceil(T*k*cf/E), i.e. ~2*k*cf*T^2*D — QUADRATIC in local tokens;
    at T = 16384 that term dwarfs the expert FFN's useful FLOPs 8x
    (scripts/profile_moe.py banks the attribution). Chunking makes it
    linear in T while staying pure MXU einsums — the router + dispatch
    build runs INSIDE the scan body, so the (chunk, E, C) tensor is
    built, consumed, and freed per iteration and nothing routing-sized
    ever exists at batch extent. Capacity becomes per-chunk
    (ceil(chunk*k*cf/E) slots per expert per chunk) — the same
    estimator change every microbatched MoE trainer accepts, and
    bitwise-identical to unchunked when nothing drops (tested). The aux
    loss is formed ONCE GLOBALLY from per-expert count/prob sums
    accumulated in the scan carry — NOT a mean of per-chunk losses
    (that was a biased estimator: the product of per-chunk means is not
    the mean of the product, so toggling dispatch_chunk used to change
    the training objective; round-5 advisor finding). Chunked and
    unchunked aux now agree to float rounding (tested near-exact).
    Under EP (`axis` set) chunking is rejected:
    each shard already routes only its T/P local tokens, which is the
    same quadratic-term reduction the mesh provides for free.

    dispatch_dtype overrides the dispatch tensor's dtype (default:
    x.dtype — bf16 under a bf16 compute path). jnp.bfloat16 under an
    f32 path halves the routing-tensor build/read bytes at a bounded
    cost: dispatch entries are exact {0, 1} in any float dtype, so only
    the einsum accumulation dtype changes.

    _aux_stats is the chunked scan's internal hook: the second return
    becomes router_dispatch's additive per-expert (count, prob-sum)
    stats instead of the scalar loss."""
    t, d = x.shape
    if dispatch_chunk and dispatch_chunk < t:
        if axis is not None:
            raise ValueError(
                "dispatch_chunk is the SINGLE-DEVICE quadratic-dispatch "
                f"lever; under EP (axis={axis!r}) the mesh already "
                "shards the routed tokens — drop one of the two"
            )
        if t % dispatch_chunk:
            raise ValueError(
                f"tokens {t} not divisible by dispatch_chunk "
                f"{dispatch_chunk}"
            )

        def chunk_body(carry, xc):
            count_sum, prob_sum = carry
            yc, (f, p) = moe_mlp(
                xc, params, n_experts=n_experts,
                capacity_factor=capacity_factor, axis=None, top_k=top_k,
                dispatch_dtype=dispatch_dtype, _aux_stats=True,
            )
            return (count_sum + f, prob_sum + p), yc

        xs = x.reshape(t // dispatch_chunk, dispatch_chunk, d)
        zero = jnp.zeros((n_experts,), jnp.float32)
        (count_sum, prob_sum), ys = lax.scan(chunk_body, (zero, zero), xs)
        # The GLOBAL Switch balance loss from the accumulated sufficient
        # statistics: identical objective to unchunked routing (only the
        # summation order differs — near-exact, tested).
        aux = jnp.sum(
            (count_sum / t) * (prob_sum / t)
        ) * n_experts
        return ys.reshape(t, d), aux
    capacity = max(1, -int(-t * top_k * capacity_factor // n_experts))  # ceil
    # Fused router (router_dispatch): ONE (T, E, C) tensor built directly
    # in the einsum dtype + a (T, E) gate map — never an f32 build/cast
    # round-trip, never a second (T, E, C) combine tensor.
    with annotate("ep.router_build"):
        dispatch, gate_te, aux = router_dispatch(
            x, params["gate"], n_experts, capacity, k=top_k,
            dtype=dispatch_dtype or x.dtype, return_stats=_aux_stats,
        )
    with annotate("ep.dispatch_einsum"):
        expert_in = jnp.einsum("tec,td->ecd", dispatch, x)    # (E, C, D)

    if axis is None:
        with annotate("ep.expert_ffn"):
            expert_out = _expert_ffn(expert_in, params["w1"], params["w2"])
    else:
        p = lax.axis_size(axis)
        if n_experts % p:
            raise ValueError(f"experts {n_experts} not divisible by axis size {p}")
        e_local = n_experts // p
        if params["w1"].shape[0] == e_local:
            # Pre-sharded stacks (moe_param_specs): O(E/P) param memory —
            # the standalone EP layer's layout.
            w1, w2 = params["w1"], params["w2"]
        elif params["w1"].shape[0] == n_experts:
            # Replicated full stacks, sliced to this device's experts by
            # axis index — the layout a replicated-params train step
            # (e.g. the SP LM step) provides. Compute/token routing is
            # still expert-parallel; only param memory is not scaled.
            # Gradient note: the dynamic_slice transpose scatters each
            # expert's cotangent into its rows on exactly one device, so
            # a pmean over the axis yields the same (1/P)-scaled gradient
            # as every replicated leaf.
            me = lax.axis_index(axis)
            w1 = lax.dynamic_slice_in_dim(params["w1"], me * e_local, e_local, 0)
            w2 = lax.dynamic_slice_in_dim(params["w2"], me * e_local, e_local, 0)
        else:
            raise ValueError(
                f"w1 holds {params['w1'].shape[0]} experts; expected "
                f"{e_local} (sharded over {axis!r}) or {n_experts} "
                "(replicated)"
            )
        # (E, C, D) -> (E/P, P*C, D): every device receives the slots
        # destined for ITS experts from every device.
        with annotate("ep.all_to_all_dispatch"):
            expert_in = lax.all_to_all(
                expert_in, axis, split_axis=0, concat_axis=1, tiled=True
            )
        with annotate("ep.expert_ffn"):
            expert_out = _expert_ffn(expert_in, w1, w2)
        # Inverse: (E/P, P*C, D) -> (E, C, D), back on the tokens' owner.
        with annotate("ep.all_to_all_combine"):
            expert_out = lax.all_to_all(
                expert_out, axis, split_axis=1, concat_axis=0, tiled=True
            )

    with annotate("ep.combine_einsum"):
        if top_k == 1:
            # Switch routing: each token occupies at most ONE (e, c)
            # slot, so the gate is a per-token SCALAR — contract the
            # SAME dispatch tensor the forward path already built and
            # scale the (T, D) result. No (T, E, C) combine tensor
            # exists at all: the routing-tensor traffic drops from
            # 2 writes + 2 reads to 1 write + 2 reads. Exact: the one
            # nonzero product per row makes the reassociation bitwise.
            gate_t = jnp.sum(gate_te, axis=-1)            # (T,)
            y = jnp.einsum("tec,ecd->td", dispatch, expert_out)
            y = y * gate_t.astype(y.dtype)[:, None]
        else:
            # Top-k: the combine weights are ONE broadcast multiply of
            # the dispatch tensor by the (T, E) gate map — never a
            # second routed build (the old form assembled combine from
            # k more one-hot products in f32 and cast it). Exact:
            # dispatch entries are {0, 1} and each (t, e) pair carries
            # at most one choice's gate.
            combine = dispatch * gate_te.astype(dispatch.dtype)[:, :, None]
            y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y.astype(x.dtype), aux


def moe_param_specs(axis: str = EXPERT_AXIS) -> dict:
    """PartitionSpecs for init_moe_params' pytree: expert stacks sharded
    on their leading (expert) dim — per-device memory O(E/P), the point
    of EP — gate replicated (every device routes its own tokens)."""
    return {"gate": P(), "w1": P(axis), "w2": P(axis)}


def moe_mlp_inference(x, params: dict, *, n_experts: int, top_k: int = 1):
    """No-drop top-k MoE for INFERENCE: every token runs through every
    expert and the router's choice(s) select (and weight) the output.

    E-fold MLP FLOPs, but O(T*E*H) memory instead of the dispatch
    formulation's O(T^2) no-drop tensors — and, unlike capacity routing,
    token t's output depends on token t alone (no batch contamination, no
    causality leak through queue positions). The right trade for decode
    and prefill; training keeps the capacity-dropped dispatch (moe_mlp).
    top_k > 1 mirrors topk_dispatch's renormalized combined gates.
    """
    probs = jax.nn.softmax((x @ params["gate"]).astype(jnp.float32), axis=-1)
    vals, idx = jax.lax.top_k(probs, top_k)                   # (T, k)
    gates = (
        vals if top_k == 1
        else vals / jnp.sum(vals, axis=-1, keepdims=True)
    )  # same gate rule as topk_dispatch
    h = jax.nn.relu(jnp.einsum("td,edh->teh", x, params["w1"]))
    y_all = jnp.einsum("teh,ehd->ted", h, params["w2"])       # (T, E, D)
    weight = jnp.zeros_like(probs)
    weight = jnp.put_along_axis(
        weight, idx, gates, axis=-1, inplace=False
    )                                                          # (T, E)
    y = jnp.einsum("ted,te->td", y_all, weight.astype(y_all.dtype))
    return y.astype(x.dtype)


def make_moe_layer(mesh, *, n_experts, capacity_factor=1.25, axis=EXPERT_AXIS,
                   top_k=1):
    """jitted (params, x) -> (y, aux) with x: (T, D) sharded on `axis` and
    the expert stacks sharded per moe_param_specs — the wrapped EP layer
    for standalone use. Pass full (host) params; shard_map's in_specs
    place each device's expert slice."""

    if n_experts % mesh.shape[axis]:
        raise ValueError(
            f"experts {n_experts} not divisible by {axis!r} size "
            f"{mesh.shape[axis]}"
        )
    body = partial(
        moe_mlp, n_experts=n_experts, capacity_factor=capacity_factor,
        axis=axis, top_k=top_k,
    )

    def shard_body(p_, x_):
        y, aux = body(x_, p_)
        # aux is computed on local tokens; average it so the replicated
        # out_spec is truthful.
        return y, lax.pmean(aux, axis)

    def fn(params, x):
        return jax.shard_map(
            shard_body,
            mesh=mesh,
            in_specs=(moe_param_specs(axis), P(axis)),
            out_specs=(P(axis), P()),
            check_vma=False,
        )(params, x)

    return jax.jit(fn)


def make_ep_lm_train_step(
    model,
    optimizer,
    mesh,
    *,
    data_axis: str | None = None,
    attn_impl: str = "oracle",
    donate: bool = True,
    remat: bool = False,
    moe_aux_weight: float = 0.01,
    compute_dtype=None,
    ce_chunk: int = 0,
    grad_accum: int = 1,
):
    """Expert-parallel LM training WITHOUT a sequence axis — the
    standard Switch/GShard deployment (EP x DP): tokens shard their
    BATCH dim over ('data'?, 'expert') jointly, so attention and every
    dense op run as plain data parallelism across both axes, while each
    MoE block's dispatch all_to_alls tokens to the expert shards over
    'expert' (each rank computes E/P experts; parallel/sp.py's EP x SP
    rides the 'seq' axis instead — this path serves MoE scale when the
    sequence fits one device). Params replicated; grads/loss pmean over
    both axes (different tokens per shard).

    step(state, tokens, targets) -> (state, {"loss": ...}); tokens
    (B, S) int32 with B sharded over (data, expert).
    """
    import optax

    from ..train.lm import get_attn_fn, lm_loss

    if not model.moe_experts:
        raise ValueError(
            "an 'expert' mesh axis needs an MoE model (--moe-experts); "
            "for dense models the axis is just data parallelism — use "
            "a 'data' axis"
        )
    n_exp = mesh.shape[EXPERT_AXIS]
    if model.moe_experts % n_exp:
        raise ValueError(
            f"experts {model.moe_experts} not divisible by expert-axis "
            f"size {n_exp}"
        )
    attn_fn = get_attn_fn(attn_impl)
    reduce_axes = tuple(a for a in (data_axis, EXPERT_AXIS) if a)

    def step(state, tokens, targets):
        # dp.py's shared accumulation; the dispatch all_to_alls run
        # uniformly per micro-batch on every rank. Per-micro-batch
        # expert capacity is a (documented) estimator change, exactly
        # like every microbatched MoE trainer.
        if grad_accum > 1 and tokens.shape[0] % grad_accum:
            raise ValueError(
                f"per-shard batch {tokens.shape[0]} not divisible by "
                f"grad_accum {grad_accum}"
            )
        from .dp import local_grads_no_aux

        loss, grads = local_grads_no_aux(
            lambda p, t, g: lm_loss(
                model, p, t, g, attn_fn=attn_fn,
                compute_dtype=compute_dtype, remat=remat,
                moe_aux_weight=moe_aux_weight, ce_chunk=ce_chunk,
                moe_axis=EXPERT_AXIS,
            ),
            state["params"], tokens, targets, grad_accum,
        )
        grads = lax.pmean(grads, reduce_axes)
        loss = lax.pmean(loss, reduce_axes)
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        return (
            {"params": params, "opt_state": opt_state,
             "step": state["step"] + 1},
            {"loss": loss},
        )

    bspec = P((data_axis, EXPERT_AXIS) if data_axis else EXPERT_AXIS)
    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(), bspec, bspec),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return donate_jit(sharded, donate=donate)
