"""Device mesh construction.

The reference's "mesh" is MPI_COMM_WORLD with contiguous rank sharding
(cnnmpi.c:456-458). Here: a `jax.sharding.Mesh` with named axes. Only the
'data' axis is populated by the shipped configs (the reference implements
only DP, SURVEY.md §2 parallelism checklist), but every entry point takes
the axis dict so a 'model' axis slots in without API change — the TP/PP
seam SURVEY.md §7 stage 5 calls for.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"


def local_device_count() -> int:
    return jax.local_device_count()


def describe_mesh(mesh: Mesh) -> dict:
    """JSON-able mesh summary for checkpoint manifests (axis name ->
    size, plus the device count): what topology-change-tolerant restore
    records at save time and compares at resume time (ISSUE 5). Axis
    ORDER is preserved — it is part of the device layout."""
    return {"axes": dict(mesh.shape), "devices": int(mesh.size)}


def make_mesh(axes: dict[str, int] | None = None, *, devices=None) -> Mesh:
    """Build a Mesh from an axis-name -> size dict.

    axes=None means {'data': all visible devices} — the twin of the
    reference's mpirun -np N world (Makefile:44). The axis sizes must
    multiply to the device count used.
    """
    if devices is None:
        devices = jax.devices()
    if axes is None:
        axes = {DATA_AXIS: len(devices)}
    sizes = list(axes.values())
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh {axes} needs {total} devices, have {len(devices)}")
    dev_array = np.array(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))
