"""Fully-sharded data parallelism (ZeRO-style) over the 'data' mesh axis.

Plain DP (dp.py) replicates every parameter on every device — fine for the
reference's 360k params, wasteful at scale. FSDP shards the parameters
(and, because the optimizer state is built FROM the sharded params,
every momentum/accumulator buffer too) across the SAME axis the batch is
sharded on: per-device parameter memory drops P-fold, and XLA's GSPMD
partitioner inserts the all-gather right before each weight is used in
forward/backward and a reduce-scatter for its gradient — the ZeRO-3
schedule, derived by the compiler instead of hand-written.

The reference has nothing like this (every rank holds all parameters,
cnnmpi.c:93-103). Like TP (tp.py), the train step is the *plain* jitted
step — sharding lives entirely in the placement of the state, so this
module is mostly spec selection, and the TP step/scan builders are reused
as-is.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS

__all__ = ["fsdp_specs", "shard_params_fsdp", "make_fsdp_state",
           "state_specs"]


def state_specs(state):
    """The PartitionSpec tree of a PLACED state — what a shard_map step
    consumes as in/out specs (parallel/sp.py state_specs). Read from the
    placement itself so the two can never disagree; freshly created
    scalar leaves (SingleDeviceSharding — e.g. adamw's count, made by
    optimizer.init outside any device_put) are replicated by
    construction."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda a: (
            a.sharding.spec
            if isinstance(a.sharding, NamedSharding) else P()
        ),
        state,
    )


def fsdp_specs(params, mesh, axis: str = DATA_AXIS, base_specs=None):
    """A PartitionSpec per leaf: shard the largest dim divisible by the
    axis size (ties broken toward the earliest dim); leaves with no such
    dim (scalars, tiny heads) stay replicated.

    base_specs (optional, same tree structure) composes FSDP with TP:
    dims already claimed by the base spec (e.g. features over 'model')
    are kept, and the 'data' shard goes on the largest REMAINING dim —
    the ZeRO-over-Megatron layout."""
    n = mesh.shape.get(axis, 1)

    def spec(leaf, base: P | None = None) -> P:
        taken = tuple(base) if base is not None else ()
        taken = taken + (None,) * (leaf.ndim - len(taken))

        def out(entries):
            # P(None, ...) and P() place identically, but compare unequal;
            # normalize all-None to the canonical empty spec.
            return P(*entries) if any(e is not None for e in entries) else P()

        if n <= 1 or leaf.ndim == 0:
            return out(taken)
        best = None
        for d in range(leaf.ndim):
            if taken[d] is not None:
                continue
            if leaf.shape[d] % n == 0 and leaf.shape[d] >= n:
                if best is None or leaf.shape[d] > leaf.shape[best]:
                    best = d
        if best is None:
            return out(taken)
        return out([
            axis if i == best else taken[i] for i in range(leaf.ndim)
        ])

    if base_specs is None:
        return jax.tree.map(spec, params)
    return jax.tree.map(
        spec, params, base_specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params_fsdp(params, mesh, axis: str = DATA_AXIS, base_specs=None):
    """Place a host/replicated param pytree with FSDP shardings."""
    specs = fsdp_specs(params, mesh, axis, base_specs)
    return jax.device_put(
        params,
        jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
    )


def make_fsdp_state(params, optimizer, mesh, axis: str = DATA_AXIS,
                    base_specs=None):
    """Train state with FSDP-sharded params; optimizer.init on the sharded
    params makes every optimizer buffer inherit the same shardings
    leaf-for-leaf (ZeRO's optimizer-state sharding for free). base_specs
    composes with TP (see fsdp_specs)."""
    import jax.numpy as jnp

    params = shard_params_fsdp(params, mesh, axis, base_specs)
    return {
        "params": params,
        "opt_state": optimizer.init(params),
        "step": jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
        ),
    }
